"""jaxlint test suite: per-rule true-positive/true-negative fixtures,
suppression handling, baseline mechanics, CLI exit codes — and the tier-1
tree-is-clean gate.

Every true-positive fixture reproduces the REAL bug pattern its rule was
derived from (see docs/STATIC_ANALYSIS.md); every true-negative is the
corrected idiom this repo actually uses. The analyzer is stdlib-only, so
none of this needs jax.
"""

import json
import os
import subprocess
import sys

import pytest

from gan_deeplearning4j_tpu.analysis import (
    DEFAULT_BASELINE_PATH,
    RULES,
    analyze_paths,
    analyze_source,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(report):
    return [f.code for f in report.active]


def run(src, path="fx/mod.py", **kw):
    return analyze_source(src, path=path, **kw)


# ===========================================================================
# JG001 — PRNG key reuse
# ===========================================================================

class TestPrngKeyReuse:
    def test_true_positive_straight_line_reuse(self):
        # the hazard class round-2 VERDICT weak #5 flagged: two draws off
        # one key correlate z_fake and z_gan forever
        r = run(
            "import jax\n"
            "def f(key, b, z):\n"
            "    z_fake = jax.random.uniform(key, (b, z), minval=-1.0)\n"
            "    z_gan = jax.random.uniform(key, (b, z), minval=-1.0)\n"
            "    return z_fake, z_gan\n"
        )
        assert codes(r) == ["JG001"]
        assert "already consumed" in r.active[0].message

    def test_true_positive_loop_replay(self):
        r = run(
            "import jax\n"
            "def f(key):\n"
            "    outs = []\n"
            "    for _ in range(4):\n"
            "        outs.append(jax.random.normal(key, (3,)))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG001"]
        assert "replays the same stream" in r.active[0].message

    def test_true_negative_split_between_draws(self):
        # the fused-iteration idiom: fold_in per step, split per consumer
        r = run(
            "import jax\n"
            "def f(key, b, z, t):\n"
            "    k1, k2 = jax.random.split(jax.random.fold_in(key, t))\n"
            "    a = jax.random.uniform(k1, (b, z))\n"
            "    c = jax.random.uniform(k2, (b, z))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_true_negative_subscripted_keys_are_distinct(self):
        # mfu_ceiling's ks = split(...); ks[0] vs ks[3] is NOT reuse
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    ks = jax.random.split(key, 6)\n"
            "    a = jax.random.uniform(ks[0], (b,))\n"
            "    c = jax.random.uniform(ks[3], (b,))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_true_negative_loop_key_is_loop_target(self):
        # eval/fid.py's frozen-kernel loop: key comes from zip over split keys
        r = run(
            "import jax\n"
            "def f(key, stages):\n"
            "    keys = jax.random.split(key, len(stages))\n"
            "    out = []\n"
            "    for k, s in zip(keys, stages):\n"
            "        out.append(jax.random.normal(k, (s, s)))\n"
            "    return out\n"
        )
        assert codes(r) == []

    def test_rebinding_retires_the_key(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    key = jax.random.fold_in(key, 1)\n"
            "    c = jax.random.uniform(key, (b,))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_stdlib_random_is_not_jax(self):
        r = run(
            "import random\n"
            "def f():\n"
            "    return random.uniform(0, 1) + random.uniform(0, 1)\n"
        )
        assert codes(r) == []

    def test_aliased_import_resolves(self):
        r = run(
            "import jax.random as jr\n"
            "def f(key, b):\n"
            "    return jr.uniform(key, (b,)) + jr.normal(key, (b,))\n"
        )
        assert codes(r) == ["JG001"]


# ===========================================================================
# JG002 — stale-fence timing
# ===========================================================================

class TestStaleFenceTiming:
    # the mfu_ceiling.py bug, de-lambdafied: fence on the warmup output
    TP_LOOP = (
        "import time\n"
        "import numpy as np\n"
        "def bench(loop, a, b):\n"
        "    out = loop(a, b)\n"
        "    times = []\n"
        "    while sum(times) < 3.0:\n"
        "        t0 = time.perf_counter()\n"
        "        loop(a, b)\n"
        "        np.asarray(out[0, 0])\n"
        "        times.append(time.perf_counter() - t0)\n"
        "    return times\n"
    )
    # the literal call-site shape of the round-5 bug: a zero-arg sync lambda
    # closing over the warmup output
    TP_CALLBACK = (
        "import numpy as np\n"
        "def bench(timed, loop, a, b):\n"
        "    out = loop(a, b)\n"
        "    return timed(lambda: loop(a, b), lambda: np.asarray(out[0, 0]))\n"
    )

    def test_true_positive_in_loop_stale_fence(self):
        r = run(self.TP_LOOP)
        assert codes(r) == ["JG002"]
        assert "stale value" in r.active[0].message

    def test_true_positive_zero_arg_sync_callback(self):
        r = run(self.TP_CALLBACK)
        assert codes(r) == ["JG002"]
        assert "zero-argument sync callback" in r.active[0].message

    def test_true_negative_fence_on_fresh_output(self):
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def bench(loop, a, b):\n"
            "    times = []\n"
            "    while sum(times) < 3.0:\n"
            "        t0 = time.perf_counter()\n"
            "        out = loop(a, b)\n"
            "        np.asarray(out[0, 0])\n"
            "        times.append(time.perf_counter() - t0)\n"
            "    return times\n"
        )
        assert codes(r) == []

    def test_true_negative_sync_callback_takes_output(self):
        # the fixed _timed_calls call shape
        r = run(
            "import numpy as np\n"
            "def bench(timed, loop, a, b):\n"
            "    return timed(lambda: loop(a, b), lambda out: np.asarray(out[0, 0]))\n"
        )
        assert codes(r) == []

    def test_true_negative_chunk_loop_fences_rebound_losses(self):
        # bench.py's run_chunk: fence AFTER the inner loop, losses rebound
        # inside it — the pipelined-chunk idiom must not fire
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def run_chunk(step, n):\n"
            "    t0 = time.perf_counter()\n"
            "    for _ in range(n):\n"
            "        losses = step()\n"
            "    np.asarray(next(iter(losses.values())))\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_fixed_mfu_ceiling_is_clean(self):
        rep = analyze_paths([os.path.join("scripts", "mfu_ceiling.py")],
                            root=REPO)
        assert [f for f in rep.active if f.code == "JG002"] == []


# ===========================================================================
# JG003 — bare assert in non-test code
# ===========================================================================

class TestBareAssert:
    def test_true_positive(self):
        # the pre-round-6 bench.py Reporter.emit guard
        r = run(
            "MAX = 1900\n"
            "def emit(line):\n"
            "    assert len(line) < MAX, 'oversize'\n"
            "    return line\n"
        )
        assert codes(r) == ["JG003"]

    def test_true_negative_explicit_raise(self):
        r = run(
            "MAX = 1900\n"
            "def emit(line):\n"
            "    if len(line) >= MAX:\n"
            "        raise ValueError('oversize')\n"
            "    return line\n"
        )
        assert codes(r) == []

    def test_test_files_are_exempt(self):
        src = "def test_x():\n    assert 1 + 1 == 2\n"
        assert codes(run(src, path="tests/test_x.py")) == []
        assert codes(run(src, path="fx/prod.py")) == ["JG003"]


# ===========================================================================
# JG004 — recompilation hazards
# ===========================================================================

class TestRecompilationHazard:
    def test_true_positive_jit_in_loop(self):
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    outs = []\n"
            "    for x in xs:\n"
            "        outs.append(jax.jit(lambda v: v * 2)(x))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG004"]
        assert "inside a loop" in r.active[0].message

    def test_true_positive_jitted_def_in_loop(self):
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    outs = []\n"
            "    for x in xs:\n"
            "        @jax.jit\n"
            "        def step(v):\n"
            "            return v * 2\n"
            "        outs.append(step(x))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG004"]

    def test_true_positive_unhashable_static_arg(self):
        r = run(
            "import jax\n"
            "def g(x, shape):\n"
            "    return x.reshape(shape)\n"
            "f = jax.jit(g, static_argnums=(1,))\n"
            "y = f(1, [2, 3])\n"
        )
        assert codes(r) == ["JG004"]
        assert "unhashable" in r.active[0].message

    def test_true_negative_build_once_call_in_loop(self):
        # this repo's _build_* idiom: construct outside, call inside
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    step = jax.jit(lambda v: v * 2)\n"
            "    return [step(x) for x in xs]\n"
        )
        assert codes(r) == []

    def test_true_negative_hashable_static_arg(self):
        r = run(
            "import jax\n"
            "def g(x, shape):\n"
            "    return x.reshape(shape)\n"
            "f = jax.jit(g, static_argnums=(1,))\n"
            "y = f(1, (2, 3))\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG005 — host sync inside traced code
# ===========================================================================

class TestHostSyncInTracedCode:
    def test_true_positive_print_in_scan_body(self):
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        print(carry)\n"
            "        return carry + x, ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == ["JG005"]
        assert "TRACE time" in r.active[0].message

    def test_true_positive_float_in_jitted_def(self):
        r = run(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x) * 2\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_positive_np_asarray_in_jit_arg(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "def outer():\n"
            "    return jax.jit(lambda x: np.asarray(x).sum())\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_positive_item_in_scan_body(self):
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(c, x):\n"
            "        return c + x.item(), ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_negative_shape_arithmetic(self):
        # static under tracing, idiomatic everywhere in the harness
        r = run(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    n = int(x.shape[0])\n"
            "    return x * n + float(len(x.shape))\n"
        )
        assert codes(r) == []

    def test_true_negative_host_call_outside_traced_code(self):
        # bench/profile scripts fence on np.asarray AFTER the jitted call —
        # that is the protocol, not a hazard
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "def measure(step):\n"
            "    losses = step()\n"
            "    return np.asarray(next(iter(losses.values())))\n"
        )
        assert codes(r) == []

    def test_true_negative_jnp_asarray_is_on_device(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return jnp.asarray(x) * 2\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG006 — donation safety
# ===========================================================================

class TestDonationSafety:
    def test_true_positive_read_after_donate(self):
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    out = step(state, xs)\n"
            "    return out + state.mean()\n"
        )
        assert codes(r) == ["JG006"]
        assert "donated" in r.active[0].message

    def test_true_positive_loop_without_rebind(self):
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    outs = [step(state, x) for x in xs]\n"
            "    return outs\n"
        )
        # same buffer donated on every iteration after the first
        assert codes(r) == ["JG006"]

    def test_true_negative_rebind_idiom(self):
        # state, loss = step(state, ...) — every call site in this repo
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    for x in xs:\n"
            "        state = step(state, x)\n"
            "    return state\n"
        )
        assert codes(r) == []

    def test_builder_kwargs_idiom_is_resolved(self):
        # harness/experiment.py + models/wgan_gp.py: _build_x returns
        # jax.jit(body, **kwargs) with donate_argnums in a kwargs literal,
        # bound via self.attr = self._build_x()
        src = (
            "import jax\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._step = self._build()\n"
            "    def _build(self):\n"
            "        def step(s, x):\n"
            "            return s + x\n"
            "        kwargs = {'donate_argnums': (0,)}\n"
            "        return jax.jit(step, **kwargs)\n"
            "    def run_bad(self, state, xs):\n"
            "        new = self._step(state, xs)\n"
            "        return new, state.sum()\n"
        )
        r = run(src)
        assert codes(r) == ["JG006"]
        clean = src.replace("        return new, state.sum()\n", "        return new\n")
        assert codes(run(clean)) == []

    def test_true_negative_donated_position_not_tracked_name(self):
        # freshly-constructed argument expressions cannot alias a live name
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(make_state, xs):\n"
            "    out = step(make_state(), xs)\n"
            "    return out\n"
        )
        assert codes(r) == []


# ===========================================================================
# engine mechanics: suppression, baseline, fingerprints, CLI
# ===========================================================================

SUPPRESSED_SRC = (
    "import jax\n"
    "def f(key, b):\n"
    "    a = jax.random.uniform(key, (b,))\n"
    "    c = jax.random.uniform(key, (b,))  # jaxlint: disable=JG001\n"
    "    return a, c\n"
)


class TestSuppression:
    def test_trailing_comment_suppresses_and_is_counted(self):
        r = run(SUPPRESSED_SRC)
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG001"]

    def test_wrong_code_does_not_suppress(self):
        r = run(SUPPRESSED_SRC.replace("disable=JG001", "disable=JG003"))
        assert codes(r) == ["JG001"]

    def test_disable_all(self):
        r = run(SUPPRESSED_SRC.replace("disable=JG001", "disable=all"))
        assert codes(r) == []
        assert len(r.suppressed) == 1

    def test_multiline_statement_suppressed_from_any_span_line(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.uniform(\n"
            "        key, (b,)  # jaxlint: disable=JG001\n"
            "    )\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_suppression_inside_string_literal_is_ignored(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.uniform(key, (b,))\n"
            "    return a, c, 'jaxlint: disable=JG001'\n"
        )
        assert codes(r) == ["JG001"]


class TestBaseline:
    TP = TestBareAssert  # convenience

    def test_baselined_finding_is_not_active(self):
        src = "def f(x):\n    assert x\n"
        r = run(src, path="fx/prod.py")
        (f,) = r.active
        baseline = [{"fingerprint": f.fingerprint, "rule": "JG003",
                     "path": f.path, "justification": "known, tracked"}]
        r2 = run(src, path="fx/prod.py", baseline=baseline)
        assert r2.active == [] and len(r2.baselined) == 1
        assert r2.stale_baseline == []

    def test_stale_baseline_entry_is_reported(self):
        baseline = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
                     "path": "fx/prod.py", "justification": "was fixed"}]
        r = run("def f(x):\n    return x\n", path="fx/prod.py",
                baseline=baseline)
        assert r.active == []
        assert len(r.stale_baseline) == 1

    def test_fingerprint_survives_line_drift_but_not_edits(self):
        src = "def f(x):\n    assert x\n"
        f1 = run(src, path="fx/prod.py").active[0]
        f2 = run("# a new leading comment\n\n" + src,
                 path="fx/prod.py").active[0]
        assert f1.fingerprint == f2.fingerprint  # moved, same content
        f3 = run(src.replace("assert x", "assert x, 'msg'"),
                 path="fx/prod.py").active[0]
        assert f3.fingerprint != f1.fingerprint  # line content changed

    def test_baseline_without_justification_is_refused(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"entries": [
            {"fingerprint": "abc", "rule": "JG003", "path": "x.py"}
        ]}))
        with pytest.raises(ValueError, match="justification"):
            load_baseline(str(p))

    def test_checked_in_baseline_loads_and_every_entry_is_justified(self):
        for e in load_baseline(DEFAULT_BASELINE_PATH):
            assert str(e.get("justification", "")).strip()
            assert "TODO" not in e.get("justification", "")


class TestParseErrors:
    def test_unparseable_file_is_a_finding_not_a_crash(self):
        r = run("def broken(:\n")
        assert codes(r) == ["JG000"]


class TestCli:
    def _cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis", *args],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_clean_file_exits_zero(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("import jax\n\n\ndef f(x):\n    return x\n")
        proc = self._cli(str(p))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_finding_exits_one_and_reports_path_line(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline")
        assert proc.returncode == 1
        assert "JG003" in proc.stdout and ":2:" in proc.stdout

    def test_json_format(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline", "--format", "json")
        data = json.loads(proc.stdout)
        assert data["clean"] is False
        assert data["active"][0]["code"] == "JG003"
        assert data["active"][0]["fingerprint"]

    def test_rule_filter(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline", "--rules", "JG001")
        assert proc.returncode == 0

    def test_bogus_path_fails_loudly(self, tmp_path):
        # a typo'd CI target must not shrink the gate to whatever resolved
        proc = self._cli(str(tmp_path / "no_such_dir"), "--no-baseline")
        assert proc.returncode == 2
        assert "neither a directory nor an existing .py file" in proc.stderr


# ===========================================================================
# the tier-1 gate: the tree this repo ships is clean
# ===========================================================================

class TestTreeIsClean:
    TARGETS = ["gan_deeplearning4j_tpu", "bench.py", "scripts"]

    def test_tree_is_clean(self):
        """The acceptance invariant: the analyzer over the whole package +
        bench.py + scripts reports nothing that is not baselined-with-
        justification. A new violation fails tier-1 with the finding text."""
        rep = analyze_paths(self.TARGETS, baseline=load_baseline(), root=REPO)
        assert rep.active == [], "\n" + "\n".join(
            f.render() for f in rep.active)
        assert rep.stale_baseline == [], rep.stale_baseline

    def test_rules_all_have_fixture_coverage(self):
        # every registered rule code appears in a TP fixture test above —
        # guards against registering a rule nobody proves fires
        here = open(__file__, encoding="utf-8").read()
        for rule in RULES:
            assert f'["{rule.code}"]' in here, (
                f"rule {rule.code} has no true-positive fixture asserting "
                f"it fires")

    def test_the_analyzer_is_jax_free(self):
        # must import (and run) with no jax available: parent-side tooling
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.modules['jax'] = None\n"
             "import gan_deeplearning4j_tpu.analysis as a\n"
             "r = a.analyze_source('def f(x):\\n    assert x\\n', 'p.py')\n"
             "print(len(r.active))"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == "1"
