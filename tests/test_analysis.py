"""jaxlint test suite: per-rule true-positive/true-negative fixtures,
suppression handling, baseline mechanics, CLI exit codes — and the tier-1
tree-is-clean gate.

Every true-positive fixture reproduces the REAL bug pattern its rule was
derived from (see docs/STATIC_ANALYSIS.md); every true-negative is the
corrected idiom this repo actually uses. The analyzer is stdlib-only, so
none of this needs jax.
"""

import json
import os
import subprocess
import sys

import pytest

from gan_deeplearning4j_tpu.analysis import (
    DEFAULT_BASELINE_PATH,
    RULES,
    analyze_paths,
    analyze_source,
    analyze_sources,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(report):
    return [f.code for f in report.active]


def run(src, path="fx/mod.py", **kw):
    return analyze_source(src, path=path, **kw)


# ===========================================================================
# JG001 — PRNG key reuse
# ===========================================================================

class TestPrngKeyReuse:
    def test_true_positive_straight_line_reuse(self):
        # the hazard class round-2 VERDICT weak #5 flagged: two draws off
        # one key correlate z_fake and z_gan forever
        r = run(
            "import jax\n"
            "def f(key, b, z):\n"
            "    z_fake = jax.random.uniform(key, (b, z), minval=-1.0)\n"
            "    z_gan = jax.random.uniform(key, (b, z), minval=-1.0)\n"
            "    return z_fake, z_gan\n"
        )
        assert codes(r) == ["JG001"]
        assert "already consumed" in r.active[0].message

    def test_true_positive_loop_replay(self):
        r = run(
            "import jax\n"
            "def f(key):\n"
            "    outs = []\n"
            "    for _ in range(4):\n"
            "        outs.append(jax.random.normal(key, (3,)))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG001"]
        assert "replays the same stream" in r.active[0].message

    def test_true_negative_split_between_draws(self):
        # the fused-iteration idiom: fold_in per step, split per consumer
        r = run(
            "import jax\n"
            "def f(key, b, z, t):\n"
            "    k1, k2 = jax.random.split(jax.random.fold_in(key, t))\n"
            "    a = jax.random.uniform(k1, (b, z))\n"
            "    c = jax.random.uniform(k2, (b, z))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_true_negative_subscripted_keys_are_distinct(self):
        # mfu_ceiling's ks = split(...); ks[0] vs ks[3] is NOT reuse
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    ks = jax.random.split(key, 6)\n"
            "    a = jax.random.uniform(ks[0], (b,))\n"
            "    c = jax.random.uniform(ks[3], (b,))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_true_negative_loop_key_is_loop_target(self):
        # eval/fid.py's frozen-kernel loop: key comes from zip over split keys
        r = run(
            "import jax\n"
            "def f(key, stages):\n"
            "    keys = jax.random.split(key, len(stages))\n"
            "    out = []\n"
            "    for k, s in zip(keys, stages):\n"
            "        out.append(jax.random.normal(k, (s, s)))\n"
            "    return out\n"
        )
        assert codes(r) == []

    def test_rebinding_retires_the_key(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    key = jax.random.fold_in(key, 1)\n"
            "    c = jax.random.uniform(key, (b,))\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_stdlib_random_is_not_jax(self):
        r = run(
            "import random\n"
            "def f():\n"
            "    return random.uniform(0, 1) + random.uniform(0, 1)\n"
        )
        assert codes(r) == []

    def test_aliased_import_resolves(self):
        r = run(
            "import jax.random as jr\n"
            "def f(key, b):\n"
            "    return jr.uniform(key, (b,)) + jr.normal(key, (b,))\n"
        )
        assert codes(r) == ["JG001"]


# ===========================================================================
# JG002 — stale-fence timing
# ===========================================================================

class TestStaleFenceTiming:
    # the mfu_ceiling.py bug, de-lambdafied: fence on the warmup output
    TP_LOOP = (
        "import time\n"
        "import numpy as np\n"
        "def bench(loop, a, b):\n"
        "    out = loop(a, b)\n"
        "    times = []\n"
        "    while sum(times) < 3.0:\n"
        "        t0 = time.perf_counter()\n"
        "        loop(a, b)\n"
        "        np.asarray(out[0, 0])\n"
        "        times.append(time.perf_counter() - t0)\n"
        "    return times\n"
    )
    # the literal call-site shape of the round-5 bug: a zero-arg sync lambda
    # closing over the warmup output
    TP_CALLBACK = (
        "import numpy as np\n"
        "def bench(timed, loop, a, b):\n"
        "    out = loop(a, b)\n"
        "    return timed(lambda: loop(a, b), lambda: np.asarray(out[0, 0]))\n"
    )

    def test_true_positive_in_loop_stale_fence(self):
        r = run(self.TP_LOOP)
        assert codes(r) == ["JG002"]
        assert "stale value" in r.active[0].message

    def test_true_positive_zero_arg_sync_callback(self):
        r = run(self.TP_CALLBACK)
        assert codes(r) == ["JG002"]
        assert "zero-argument sync callback" in r.active[0].message

    def test_true_negative_fence_on_fresh_output(self):
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def bench(loop, a, b):\n"
            "    times = []\n"
            "    while sum(times) < 3.0:\n"
            "        t0 = time.perf_counter()\n"
            "        out = loop(a, b)\n"
            "        np.asarray(out[0, 0])\n"
            "        times.append(time.perf_counter() - t0)\n"
            "    return times\n"
        )
        assert codes(r) == []

    def test_true_negative_sync_callback_takes_output(self):
        # the fixed _timed_calls call shape
        r = run(
            "import numpy as np\n"
            "def bench(timed, loop, a, b):\n"
            "    return timed(lambda: loop(a, b), lambda out: np.asarray(out[0, 0]))\n"
        )
        assert codes(r) == []

    def test_true_negative_chunk_loop_fences_rebound_losses(self):
        # bench.py's run_chunk: fence AFTER the inner loop, losses rebound
        # inside it — the pipelined-chunk idiom must not fire
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def run_chunk(step, n):\n"
            "    t0 = time.perf_counter()\n"
            "    for _ in range(n):\n"
            "        losses = step()\n"
            "    np.asarray(next(iter(losses.values())))\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_fixed_mfu_ceiling_is_clean(self):
        rep = analyze_paths([os.path.join("scripts", "mfu_ceiling.py")],
                            root=REPO)
        assert [f for f in rep.active if f.code == "JG002"] == []


# ===========================================================================
# JG003 — bare assert in non-test code
# ===========================================================================

class TestBareAssert:
    def test_true_positive(self):
        # the pre-round-6 bench.py Reporter.emit guard
        r = run(
            "MAX = 1900\n"
            "def emit(line):\n"
            "    assert len(line) < MAX, 'oversize'\n"
            "    return line\n"
        )
        assert codes(r) == ["JG003"]

    def test_true_negative_explicit_raise(self):
        r = run(
            "MAX = 1900\n"
            "def emit(line):\n"
            "    if len(line) >= MAX:\n"
            "        raise ValueError('oversize')\n"
            "    return line\n"
        )
        assert codes(r) == []

    def test_test_files_are_exempt(self):
        src = "def test_x():\n    assert 1 + 1 == 2\n"
        assert codes(run(src, path="tests/test_x.py")) == []
        assert codes(run(src, path="fx/prod.py")) == ["JG003"]


# ===========================================================================
# JG004 — recompilation hazards
# ===========================================================================

class TestRecompilationHazard:
    def test_true_positive_jit_in_loop(self):
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    outs = []\n"
            "    for x in xs:\n"
            "        outs.append(jax.jit(lambda v: v * 2)(x))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG004"]
        assert "inside a loop" in r.active[0].message

    def test_true_positive_jitted_def_in_loop(self):
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    outs = []\n"
            "    for x in xs:\n"
            "        @jax.jit\n"
            "        def step(v):\n"
            "            return v * 2\n"
            "        outs.append(step(x))\n"
            "    return outs\n"
        )
        assert codes(r) == ["JG004"]

    def test_true_positive_unhashable_static_arg(self):
        r = run(
            "import jax\n"
            "def g(x, shape):\n"
            "    return x.reshape(shape)\n"
            "f = jax.jit(g, static_argnums=(1,))\n"
            "y = f(1, [2, 3])\n"
        )
        assert codes(r) == ["JG004"]
        assert "unhashable" in r.active[0].message

    def test_true_negative_build_once_call_in_loop(self):
        # this repo's _build_* idiom: construct outside, call inside
        r = run(
            "import jax\n"
            "def f(xs):\n"
            "    step = jax.jit(lambda v: v * 2)\n"
            "    return [step(x) for x in xs]\n"
        )
        assert codes(r) == []

    def test_true_negative_hashable_static_arg(self):
        r = run(
            "import jax\n"
            "def g(x, shape):\n"
            "    return x.reshape(shape)\n"
            "f = jax.jit(g, static_argnums=(1,))\n"
            "y = f(1, (2, 3))\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG005 — host sync inside traced code
# ===========================================================================

class TestHostSyncInTracedCode:
    def test_true_positive_print_in_scan_body(self):
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        print(carry)\n"
            "        return carry + x, ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == ["JG005"]
        assert "TRACE time" in r.active[0].message

    def test_true_positive_float_in_jitted_def(self):
        r = run(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x) * 2\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_positive_np_asarray_in_jit_arg(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "def outer():\n"
            "    return jax.jit(lambda x: np.asarray(x).sum())\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_positive_item_in_scan_body(self):
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(c, x):\n"
            "        return c + x.item(), ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == ["JG005"]

    def test_true_negative_shape_arithmetic(self):
        # static under tracing, idiomatic everywhere in the harness
        r = run(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    n = int(x.shape[0])\n"
            "    return x * n + float(len(x.shape))\n"
        )
        assert codes(r) == []

    def test_true_negative_host_call_outside_traced_code(self):
        # bench/profile scripts fence on np.asarray AFTER the jitted call —
        # that is the protocol, not a hazard
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "def measure(step):\n"
            "    losses = step()\n"
            "    return np.asarray(next(iter(losses.values())))\n"
        )
        assert codes(r) == []

    def test_true_negative_jnp_asarray_is_on_device(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return jnp.asarray(x) * 2\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG006 — donation safety
# ===========================================================================

class TestDonationSafety:
    def test_true_positive_read_after_donate(self):
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    out = step(state, xs)\n"
            "    return out + state.mean()\n"
        )
        assert codes(r) == ["JG006"]
        assert "donated" in r.active[0].message

    def test_true_positive_loop_without_rebind(self):
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    outs = [step(state, x) for x in xs]\n"
            "    return outs\n"
        )
        # same buffer donated on every iteration after the first
        assert codes(r) == ["JG006"]

    def test_true_negative_rebind_idiom(self):
        # state, loss = step(state, ...) — every call site in this repo
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    for x in xs:\n"
            "        state = step(state, x)\n"
            "    return state\n"
        )
        assert codes(r) == []

    def test_builder_kwargs_idiom_is_resolved(self):
        # harness/experiment.py + models/wgan_gp.py: _build_x returns
        # jax.jit(body, **kwargs) with donate_argnums in a kwargs literal,
        # bound via self.attr = self._build_x()
        src = (
            "import jax\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._step = self._build()\n"
            "    def _build(self):\n"
            "        def step(s, x):\n"
            "            return s + x\n"
            "        kwargs = {'donate_argnums': (0,)}\n"
            "        return jax.jit(step, **kwargs)\n"
            "    def run_bad(self, state, xs):\n"
            "        new = self._step(state, xs)\n"
            "        return new, state.sum()\n"
        )
        r = run(src)
        assert codes(r) == ["JG006"]
        clean = src.replace("        return new, state.sum()\n", "        return new\n")
        assert codes(run(clean)) == []

    def test_true_negative_donated_position_not_tracked_name(self):
        # freshly-constructed argument expressions cannot alias a live name
        r = run(
            "import jax\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(make_state, xs):\n"
            "    out = step(make_state(), xs)\n"
            "    return out\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG007 — discarded .at[].set() result
# ===========================================================================

class TestDiscardedAtUpdate:
    def test_true_positive_discarded_set(self):
        r = run(
            "import jax.numpy as jnp\n"
            "def f(x, i, v):\n"
            "    x.at[i].set(v)\n"
            "    return x\n"
        )
        assert codes(r) == ["JG007"]
        assert "discards" in r.active[0].message
        assert "x = x.at[i].set(v)" in r.active[0].message

    def test_true_positive_discarded_add_on_attribute(self):
        r = run(
            "import jax.numpy as jnp\n"
            "class T:\n"
            "    def bump(self, i):\n"
            "        self.counts.at[i].add(1)\n"
        )
        assert codes(r) == ["JG007"]

    def test_true_negative_rebound(self):
        r = run(
            "import jax.numpy as jnp\n"
            "def f(x, i, v):\n"
            "    x = x.at[i].set(v)\n"
            "    return x\n"
        )
        assert codes(r) == []

    def test_true_negative_result_used_as_argument_or_return(self):
        r = run(
            "import jax.numpy as jnp\n"
            "def f(x, i, v, g):\n"
            "    g(x.at[i].set(v))\n"
            "    return x.at[i].add(v)\n"
        )
        assert codes(r) == []

    def test_plain_attribute_named_at_is_not_flagged(self):
        # `obj.at[k].set(v)` requires the `.at` property shape exactly;
        # an unrelated dict-of-methods `handlers[k].set(v)` must not fire
        r = run(
            "def f(handlers, k, v):\n"
            "    handlers[k].set(v)\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG008 — float literal on the loop-carry path
# ===========================================================================

class TestScanCarryDtypeDrift:
    def test_true_positive_decay_literal_in_scan_carry(self):
        # the compounding case: 0.999 is ~0.9961 in bf16, so a 128-step
        # window turns a 0.88 decay into 0.61
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        carry = carry * 0.999 + x\n"
            "        return carry, ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == ["JG008"]
        assert "0.999" in r.active[0].message

    def test_true_positive_fori_loop_body_by_name(self):
        r = run(
            "import jax\n"
            "def body(i, val):\n"
            "    return val * 0.5\n"
            "def outer(v0):\n"
            "    return jax.lax.fori_loop(0, 10, body, v0)\n"
        )
        assert codes(r) == ["JG008"]

    def test_true_positive_cross_module_scan_body(self):
        # the body lives a module away; the finding lands in ITS file
        r = analyze_sources({
            "pkg/bodies.py": (
                "def ema_body(carry, x):\n"
                "    return carry * 0.99 + x * 0.01, carry\n"
            ),
            "pkg/driver.py": (
                "import jax\n"
                "from pkg.bodies import ema_body\n"
                "def outer(xs):\n"
                "    return jax.lax.scan(ema_body, 0.0, xs)\n"
            ),
        })
        assert codes(r) == ["JG008", "JG008"]
        assert {f.path for f in r.active} == {"pkg/bodies.py"}

    def test_true_negative_dtype_pinned_literal(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        carry = carry * jnp.asarray(0.999, carry.dtype) + x\n"
            "        return carry, ()\n"
            "    return jax.lax.scan(body, jnp.zeros(()), xs)\n"
        )
        assert codes(r) == []

    def test_true_negative_literal_on_per_step_output_only(self):
        # per-step outputs do not compound across iterations
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        y = x * 0.5\n"
            "        return carry + x, y\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == []

    def test_true_negative_integer_literal(self):
        r = run(
            "import jax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        return carry * 2 + x, ()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG009 — host callback inside a timed region
# ===========================================================================

class TestCallbackInTimedRegion:
    def test_true_positive_debug_print_in_timed_loop(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def bench(step):\n"
            "    times = []\n"
            "    for _ in range(10):\n"
            "        t0 = time.perf_counter()\n"
            "        jax.debug.print('step')\n"
            "        step()\n"
            "        times.append(time.perf_counter() - t0)\n"
            "    return times\n"
        )
        assert codes(r) == ["JG009"]
        assert "host" in r.active[0].message

    def test_true_positive_cross_module_transitive_callback(self):
        # bench times step(); step -> log_losses -> jax.debug.print, two
        # modules away — only the project index can see it
        r = analyze_sources({
            "pkg/steps.py": (
                "import jax\n"
                "def log_losses(x):\n"
                "    jax.debug.print('loss {x}', x=x)\n"
                "    return x\n"
                "def step(state):\n"
                "    return log_losses(state)\n"
            ),
            "pkg/bench.py": (
                "import time\n"
                "from pkg.steps import step\n"
                "def bench(state):\n"
                "    t0 = time.perf_counter()\n"
                "    state = step(state)\n"
                "    t1 = time.perf_counter()\n"
                "    return state, t1 - t0\n"
            ),
        })
        assert codes(r) == ["JG009"]
        assert r.active[0].path == "pkg/bench.py"
        assert "pkg.steps.step" in r.active[0].message

    def test_true_positive_relative_import_callback(self):
        # the call graph must absolutize `from .steps import step` — the
        # dominant intra-package import style of this repo
        r = analyze_sources({
            "pkg/__init__.py": "",
            "pkg/steps.py": (
                "import jax\n"
                "def step(state):\n"
                "    jax.debug.print('s')\n"
                "    return state\n"
            ),
            "pkg/bench.py": (
                "import time\n"
                "from .steps import step\n"
                "def bench(state):\n"
                "    t0 = time.perf_counter()\n"
                "    state = step(state)\n"
                "    t1 = time.perf_counter()\n"
                "    return state, t1 - t0\n"
            ),
        })
        assert codes(r) == ["JG009"]

    def test_true_negative_callback_outside_timed_region(self):
        r = analyze_sources({
            "pkg/steps.py": (
                "import jax\n"
                "def step(state):\n"
                "    jax.debug.print('s')\n"
                "    return state\n"
            ),
            "pkg/run.py": (
                "from pkg.steps import step\n"
                "def run(state):\n"
                "    return step(state)\n"
            ),
        })
        assert codes(r) == []

    def test_true_negative_fence_in_timed_loop_is_not_a_callback(self):
        # the protocol itself: fencing on np.asarray is JG002's domain
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def bench(step):\n"
            "    times = []\n"
            "    for _ in range(3):\n"
            "        t0 = time.perf_counter()\n"
            "        out = step()\n"
            "        np.asarray(out)\n"
            "        times.append(time.perf_counter() - t0)\n"
            "    return times\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG010 — donation through functools.partial / import indirection
# ===========================================================================

class TestDonationFlow:
    def test_true_positive_partial_binds_donated_position(self):
        # the captured buffer is donated on EVERY call — no safe second call
        r = run(
            "import jax\n"
            "import functools\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(0,))\n"
            "def runner(state, xs):\n"
            "    p = functools.partial(step, state)\n"
            "    return [p(x) for x in xs]\n"
        )
        assert codes(r) == ["JG010"]
        assert "EVERY call" in r.active[0].message

    def test_true_positive_shifted_position_use_after_donate(self):
        r = run(
            "import jax\n"
            "import functools\n"
            "def g(cfg, s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(1,))\n"
            "def runner(cfg, state, xs):\n"
            "    p = functools.partial(step, cfg)\n"
            "    out = p(state, xs)\n"
            "    return out + state.mean()\n"
        )
        assert codes(r) == ["JG010"]

    def test_true_positive_imported_donator(self):
        r = analyze_sources({
            "pkg/steps.py": (
                "import jax\n"
                "def _step(s, x):\n"
                "    return s + x\n"
                "step = jax.jit(_step, donate_argnums=(0,))\n"
            ),
            "pkg/run.py": (
                "from pkg.steps import step\n"
                "def runner(state, xs):\n"
                "    out = step(state, xs)\n"
                "    return out + state.mean()\n"
            ),
        })
        assert codes(r) == ["JG010"]
        assert r.active[0].path == "pkg/run.py"

    def test_true_positive_imported_builder(self):
        # step = make_step() where the builder (and its donate kwargs dict)
        # live in another module
        r = analyze_sources({
            "pkg/build.py": (
                "import jax\n"
                "def make_step():\n"
                "    def body(s, x):\n"
                "        return s + x\n"
                "    kwargs = {'donate_argnums': (0,)}\n"
                "    return jax.jit(body, **kwargs)\n"
            ),
            "pkg/run.py": (
                "from pkg.build import make_step\n"
                "step = make_step()\n"
                "def runner(state, xs):\n"
                "    out = step(state, xs)\n"
                "    return out + state.mean()\n"
            ),
        })
        assert codes(r) == ["JG010"]

    def test_true_positive_donator_through_package_reexport(self):
        # `from pkg import step` where pkg/__init__ re-exports it from the
        # defining module — the realistic import surface of this repo
        r = analyze_sources({
            "pkg/__init__.py": "from .steps import step\n",
            "pkg/steps.py": (
                "import jax\n"
                "def _step(s, x):\n"
                "    return s + x\n"
                "step = jax.jit(_step, donate_argnums=(0,))\n"
            ),
            "app.py": (
                "from pkg import step\n"
                "def runner(state, xs):\n"
                "    out = step(state, xs)\n"
                "    return out + state.mean()\n"
            ),
        })
        assert codes(r) == ["JG010"]
        assert r.active[0].path == "app.py"

    def test_true_negative_shifted_position_with_rebind(self):
        r = run(
            "import jax\n"
            "import functools\n"
            "def g(cfg, s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(1,))\n"
            "def runner(cfg, state, xs):\n"
            "    p = functools.partial(step, cfg)\n"
            "    for x in xs:\n"
            "        state = p(state, x)\n"
            "    return state\n"
        )
        assert codes(r) == []

    def test_true_negative_imported_donator_with_rebind(self):
        r = analyze_sources({
            "pkg/steps.py": (
                "import jax\n"
                "def _step(s, x):\n"
                "    return s + x\n"
                "step = jax.jit(_step, donate_argnums=(0,))\n"
            ),
            "pkg/run.py": (
                "from pkg.steps import step\n"
                "def runner(state, xs):\n"
                "    for x in xs:\n"
                "        state = step(state, x)\n"
                "    return state\n"
            ),
        })
        assert codes(r) == []

    def test_partial_alias_is_scoped_to_its_function(self):
        # a() builds a shifted partial named `p`; b() has its OWN unrelated
        # local `p` — b must not inherit a()'s donation alias by name
        r = run(
            "import jax\n"
            "import functools\n"
            "def g(cfg, s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(1,))\n"
            "def a(cfg, state, xs):\n"
            "    p = functools.partial(step, cfg)\n"
            "    for x in xs:\n"
            "        state = p(state, x)\n"
            "    return state\n"
            "def b(state):\n"
            "    p = lambda s: s\n"
            "    out = p(state)\n"
            "    return out + state.mean()\n"
        )
        assert codes(r) == []

    def test_module_level_partial_alias_is_visible_in_functions(self):
        r = run(
            "import jax\n"
            "import functools\n"
            "def g(cfg, s, x):\n"
            "    return s + x\n"
            "step = jax.jit(g, donate_argnums=(1,))\n"
            "CFG = object()\n"
            "p = functools.partial(step, CFG)\n"
            "def runner(state, xs):\n"
            "    out = p(state, xs)\n"
            "    return out + state.mean()\n"
        )
        assert codes(r) == ["JG010"]

    def test_partial_of_non_donator_is_ignored(self):
        r = run(
            "import functools\n"
            "def g(s, x):\n"
            "    return s + x\n"
            "def runner(state, xs):\n"
            "    p = functools.partial(g, state)\n"
            "    return [p(x) for x in xs] + [state.mean()]\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG011 — statically-visible pmap/vmap axis mismatch
# ===========================================================================

class TestAxisSizeMismatch:
    def test_true_positive_in_axes_vs_cross_module_arity(self):
        r = analyze_sources({
            "pkg/ops.py": (
                "def loss(params, batch, labels):\n"
                "    return ((params - batch) ** 2).sum() + labels.sum()\n"
            ),
            "pkg/run.py": (
                "import jax\n"
                "from pkg.ops import loss\n"
                "g = jax.vmap(loss, in_axes=(None, 0))\n"
            ),
        })
        assert codes(r) == ["JG011"]
        assert "pkg.ops.loss" in r.active[0].message

    def test_true_positive_in_axes_vs_call_site(self):
        r = run(
            "import jax\n"
            "def f(x, y):\n"
            "    return x + y\n"
            "def runner(x):\n"
            "    return jax.vmap(f, in_axes=(0, 0))(x)\n"
        )
        assert codes(r) == ["JG011"]

    def test_true_positive_literal_shape_mismatch(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def f(x, y):\n"
            "    return x + y\n"
            "def runner():\n"
            "    x = jnp.zeros((4, 3))\n"
            "    y = jnp.ones((5, 3))\n"
            "    return jax.vmap(f)(x, y)\n"
        )
        assert codes(r) == ["JG011"]
        assert "size 4" in r.active[0].message
        assert "size 5" in r.active[0].message

    def test_true_negative_matching_shapes_and_axes(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def f(x, y):\n"
            "    return x + y\n"
            "def runner():\n"
            "    x = jnp.zeros((4, 3))\n"
            "    y = jnp.ones((4, 3))\n"
            "    return jax.vmap(f, in_axes=(0, 0))(x, y)\n"
        )
        assert codes(r) == []

    def test_true_negative_none_axis_broadcasts(self):
        r = run(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def f(x, y):\n"
            "    return x + y\n"
            "def runner():\n"
            "    x = jnp.zeros((4, 3))\n"
            "    y = jnp.ones((5, 3))\n"
            "    return jax.vmap(f, in_axes=(0, None))(x, y)\n"
        )
        assert codes(r) == []

    def test_true_negative_unknown_shapes_are_silence(self):
        r = run(
            "import jax\n"
            "def f(x, y):\n"
            "    return x + y\n"
            "def runner(x, y):\n"
            "    return jax.vmap(f)(x, y)\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG012 — dead out_shardings on donated buffers
# ===========================================================================

class TestDeadDonatedOutSharding:
    def test_true_positive_donated_sharding_absent_from_outputs(self):
        # the donated state is replicated in but every output is resharded
        # to `data` — XLA can never alias the donated buffer; peak HBM is
        # silently double what the donation promises
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,),\n"
            "                   in_shardings=(rep, data),\n"
            "                   out_shardings=(data,))\n"
        )
        assert codes(r) == ["JG012"]
        assert "rep" in r.active[0].message
        assert "dead" in r.active[0].message

    def test_true_positive_kwargs_builder_idiom(self):
        # the harness/experiment.py builder shape: donate in the dict
        # literal, shardings assigned conditionally by subscript
        r = run(
            "import jax\n"
            "def build(f, mesh, rep, data):\n"
            "    kwargs = {'donate_argnums': (0, 1)}\n"
            "    if mesh is not None:\n"
            "        kwargs['in_shardings'] = (rep,) * 2 + (data,) * 2\n"
            "        kwargs['out_shardings'] = (data,) * 2\n"
            "    return jax.jit(f, **kwargs)\n"
        )
        assert codes(r) == ["JG012"]

    def test_true_negative_matching_sharding_present(self):
        # the repo's actual trainer shape: donated state goes in replicated
        # and comes back replicated — the donation can alias
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,),\n"
            "                   in_shardings=(rep, data, data, rep),\n"
            "                   out_shardings=(rep, rep))\n"
        )
        assert codes(r) == []

    def test_true_negative_repetition_idiom_matches(self):
        r = run(
            "import jax\n"
            "def build(f, rep, stacked, data):\n"
            "    kwargs = {'donate_argnums': (0, 1, 2, 3)}\n"
            "    kwargs['in_shardings'] = (rep,) * 4 + (stacked,) * 2 + (data,) * 2\n"
            "    kwargs['out_shardings'] = (rep,) * 4 + (rep,) * 3\n"
            "    return jax.jit(f, **kwargs)\n"
        )
        assert codes(r) == []

    def test_true_negative_no_out_shardings_declared(self):
        # without out_shardings XLA is free to alias — nothing to flag
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,), in_shardings=(rep, data))\n"
        )
        assert codes(r) == []

    def test_true_negative_unresolvable_specs_are_silence(self):
        r = run(
            "import jax\n"
            "def build(f, shardings):\n"
            "    return jax.jit(f, donate_argnums=(0,),\n"
            "                   in_shardings=shardings[0],\n"
            "                   out_shardings=shardings[1])\n"
        )
        assert codes(r) == []

    def test_single_sharding_broadcast_compares(self):
        # a lone sharding broadcasts to every input leaf; matching single
        # out_shardings means the donation can alias
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,),\n"
            "                   in_shardings=rep, out_shardings=rep)\n"
        )
        assert codes(r) == []
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,),\n"
            "                   in_shardings=rep, out_shardings=data)\n"
        )
        assert codes(r) == ["JG012"]

    def test_suppression_applies(self):
        r = run(
            "import jax\n"
            "def build(f, rep, data):\n"
            "    return jax.jit(f, donate_argnums=(0,),  # jaxlint: disable=JG012\n"
            "                   in_shardings=(rep,), out_shardings=(data,))\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG012"]


# ===========================================================================
# JG013 — sharding spec names axes the mesh does not have
# ===========================================================================

class TestMeshAxisMismatch:
    def test_true_positive_named_sharding_unknown_axis(self):
        # the spec was written for a ("data",) trainer mesh but paired with
        # the 1-D ("replica",) serving mesh — jax rejects it only at use time
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('replica',))\n"
            "    return NamedSharding(mesh, PartitionSpec('data'))\n"
        )
        assert codes(r) == ["JG013"]
        assert "'data'" in r.active[0].message
        assert "replica" in r.active[0].message

    def test_true_positive_shard_map_in_specs(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, PartitionSpec as P\n"
            "def runner(f, xs):\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('data',))\n"
            "    return jax.shard_map(f, mesh=mesh, in_specs=(P('model'),),\n"
            "                         out_specs=P('data'))(xs)\n"
        )
        assert codes(r) == ["JG013"]
        assert "in_specs" in r.active[0].message

    def test_true_positive_shard_map_positional_specs(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, PartitionSpec as P\n"
            "def runner(f, xs):\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('replica',))\n"
            "    return jax.shard_map(f, mesh, P('model'), P('replica'))(xs)\n"
        )
        assert codes(r) == ["JG013"]
        assert "in_specs" in r.active[0].message

    def test_true_positive_axis_used_twice_in_one_spec(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('data',))\n"
            "    return NamedSharding(mesh, PartitionSpec('data', 'data'))\n"
        )
        assert codes(r) == ["JG013"]
        assert "two dimensions" in r.active[0].message

    def test_true_negative_matching_axes(self):
        # the serving engine's bulk-lane shape: 1-D replica mesh, replicated
        # params + batch sharded on the replica axis
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('replica',))\n"
            "    rep = NamedSharding(mesh, PartitionSpec())\n"
            "    return rep, NamedSharding(mesh, PartitionSpec('replica'))\n"
        )
        assert codes(r) == []

    def test_true_negative_none_entries_and_tuple_axes(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('a', 'b'))\n"
            "    return NamedSharding(mesh, PartitionSpec(None, ('a', 'b')))\n"
        )
        assert codes(r) == []

    def test_true_negative_unresolvable_mesh_is_silence(self):
        # mesh comes in as a parameter — axes unknowable, no guess
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def build(mesh):\n"
            "    return NamedSharding(mesh, PartitionSpec('data'))\n"
        )
        assert codes(r) == []

    def test_true_negative_reassigned_mesh_is_silence(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build(flag):\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('a',))\n"
            "    if flag:\n"
            "        mesh = Mesh(np.asarray(jax.devices()), ('b',))\n"
            "    return NamedSharding(mesh, PartitionSpec('a'))\n"
        )
        assert codes(r) == []

    def test_true_negative_rebound_to_helper_is_silence(self):
        # first binding is a literal mesh, but the name is REBOUND to a
        # helper whose axes are unknowable — certainty is gone, so silence
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build(make_2d):\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('replica',))\n"
            "    mesh = make_2d()\n"
            "    return NamedSharding(mesh, PartitionSpec('model'))\n"
        )
        assert codes(r) == []

    def test_true_negative_parameter_default_mesh_is_silence(self):
        # the mesh may arrive from the caller — the body binding is only a
        # fallback, so axes are not statically certain
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build(mesh=None):\n"
            "    if mesh is None:\n"
            "        mesh = Mesh(np.asarray(jax.devices()), ('a',))\n"
            "    return NamedSharding(mesh, PartitionSpec('b'))\n"
        )
        assert codes(r) == []

    def test_make_mesh_axis_names_kwarg(self):
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = jax.make_mesh((4,), axis_names=('x',))\n"
            "    return NamedSharding(mesh, PartitionSpec('y'))\n"
        )
        assert codes(r) == ["JG013"]

    def test_suppression_applies(self):
        r = run(
            "import jax\n"
            "import numpy as np\n"
            "from jax.sharding import Mesh, NamedSharding, PartitionSpec\n"
            "def build():\n"
            "    mesh = Mesh(np.asarray(jax.devices()), ('replica',))\n"
            "    return NamedSharding(mesh, PartitionSpec('data'))  # jaxlint: disable=JG013\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG013"]


# ===========================================================================
# JG014 — cross-module PRNG key reuse (consumes the index's prng_params)
# ===========================================================================

_JG014_HELPERS = (
    "import jax\n"
    "def sample_z(key, n):\n"
    "    return jax.random.uniform(key, (n, 2))\n"
    "def derive_only(key, i):\n"
    "    return jax.random.fold_in(key, i)\n"
    "def outer(rng, n):\n"
    "    return sample_z(rng, n)\n"  # consumes transitively
)


class TestCrossModulePrngReuse:
    def test_true_positive_same_key_two_handoffs(self):
        # the indirection JG001 cannot see: both draws happen a module away
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    a = sample_z(key, n)\n"
                "    b = sample_z(key, n)\n"
                "    return a, b\n"
            ),
        })
        assert codes(r) == ["JG014"]
        assert "sample_z" in r.active[0].message
        assert "already consumed" in r.active[0].message

    def test_true_positive_handoff_then_direct_draw(self):
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "import jax\n"
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    a = sample_z(key, n)\n"
                "    b = jax.random.normal(key, (n,))\n"
                "    return a, b\n"
            ),
        })
        assert codes(r) == ["JG014"]

    def test_true_positive_transitive_consumer(self):
        # outer() only forwards the key — but the forward chain ends in a
        # jax.random draw, so two outer(key) calls correlate
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import outer\n"
                "def run(key, n):\n"
                "    return outer(key, n), outer(key, n)\n"
            ),
        })
        assert codes(r) == ["JG014"]

    def test_true_positive_keyword_handoff(self):
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import sample_z\n"
                "def run(k2, n):\n"
                "    a = sample_z(key=k2, n=n)\n"
                "    b = sample_z(key=k2, n=n)\n"
                "    return a, b\n"
            ),
        })
        assert codes(r) == ["JG014"]

    def test_true_positive_handoff_loop_replay(self):
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    outs = []\n"
                "    for i in range(4):\n"
                "        outs.append(sample_z(key, n))\n"
                "    return outs\n"
            ),
        })
        assert codes(r) == ["JG014"]
        assert "replays the same stream" in r.active[0].message

    def test_true_negative_split_between_handoffs(self):
        # the corrected idiom: one subkey per consumer
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "import jax\n"
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    k1, k2 = jax.random.split(key)\n"
                "    return sample_z(k1, n), sample_z(k2, n)\n"
            ),
        })
        assert codes(r) == []

    def test_true_negative_derive_only_helper(self):
        # the experiment's wkey idiom: the helper only fold_ins — handing
        # it the same base key with different salts is the POINT
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import derive_only\n"
                "def run(key):\n"
                "    return derive_only(key, 0), derive_only(key, 1)\n"
            ),
        })
        assert codes(r) == []

    def test_true_negative_rebinding_retires_key(self):
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "import jax\n"
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    a = sample_z(key, n)\n"
                "    key = jax.random.fold_in(key, 1)\n"
                "    b = sample_z(key, n)\n"
                "    return a, b\n"
            ),
        })
        assert codes(r) == []

    def test_true_negative_unresolvable_callee_is_silence(self):
        # callee not in the index: no facts, no guess
        r = analyze_sources({
            "pkg/main.py": (
                "from somewhere_else import sample_z\n"
                "def run(key, n):\n"
                "    return sample_z(key, n), sample_z(key, n)\n"
            ),
        })
        assert codes(r) == []

    def test_true_negative_non_prng_param_is_silence(self):
        # the repeated argument lands on a parameter the summary does NOT
        # mark PRNG-like — repetition is fine
        r = analyze_sources({
            "pkg/helpers.py": (
                "import jax\n"
                "def fit(cfg, key):\n"
                "    return jax.random.normal(key, (cfg,))\n"
            ),
            "pkg/main.py": (
                "import jax\n"
                "from pkg.helpers import fit\n"
                "def run(cfg, key):\n"
                "    k1, k2 = jax.random.split(key)\n"
                "    return fit(cfg, k1), fit(cfg, k2)\n"
            ),
        })
        assert codes(r) == []

    def test_direct_direct_pairs_stay_jg001(self):
        # one defect, one code: both uses direct ⇒ JG001 fires, JG014 not
        r = run(
            "import jax\n"
            "def run(key, n):\n"
            "    a = jax.random.normal(key, (n,))\n"
            "    b = jax.random.normal(key, (n,))\n"
            "    return a, b\n"
        )
        assert sorted(codes(r)) == ["JG001"]

    def test_skips_test_modules(self):
        # tests reuse keys deliberately (determinism assertions)
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "tests/test_x.py": (
                "from pkg.helpers import sample_z\n"
                "def test_same_key_is_deterministic(key):\n"
                "    assert (sample_z(key, 3) == sample_z(key, 3)).all()\n"
            ),
        })
        assert "JG014" not in codes(r)

    def test_suppression_applies(self):
        r = analyze_sources({
            "pkg/helpers.py": _JG014_HELPERS,
            "pkg/main.py": (
                "from pkg.helpers import sample_z\n"
                "def run(key, n):\n"
                "    a = sample_z(key, n)\n"
                "    b = sample_z(key, n)  # jaxlint: disable=JG014\n"
                "    return a, b\n"
            ),
        })
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG014"]


# ===========================================================================
# JG015 — unfenced clock delta fed to a telemetry sink
# ===========================================================================

class TestTelemetryUnfencedTiming:
    def test_true_positive_inline_delta_to_observe(self):
        # the hazard the telemetry plane makes one line to write: a
        # perf-counter delta around a jitted call, observed into a
        # histogram with no fence — the metric records dispatch latency
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, hist):\n"
            "    jf = jax.jit(step)\n"
            "    t0 = time.perf_counter()\n"
            "    y = jf(x)\n"
            "    hist.observe(time.perf_counter() - t0)\n"
            "    return y\n"
        )
        assert codes(r) == ["JG015"]
        assert "dispatch, not execution" in r.active[0].message

    def test_true_positive_named_delta_to_stage_add(self):
        # the StageStats.add shape, with the delta bound to a name first
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, stats):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.jit(step)(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    stats.add('device', dt)\n"
            "    return y\n"
        )
        assert codes(r) == ["JG015"]

    def test_true_positive_cross_module_traced_callee(self):
        # the jit lives a module away: the project index's traced-ness
        # summary is what convicts the call site
        r = analyze_sources({
            "pkg/steps.py": (
                "import jax\n"
                "@jax.jit\n"
                "def train_step(x):\n"
                "    return x * 2\n"
            ),
            "pkg/loop.py": (
                "import time\n"
                "from pkg.steps import train_step\n"
                "def run(x, hist):\n"
                "    t0 = time.perf_counter()\n"
                "    y = train_step(x)\n"
                "    hist.observe(time.perf_counter() - t0)\n"
                "    return y\n"
            ),
        })
        assert codes(r) == ["JG015"]

    def test_true_positive_fence_after_the_delta_is_too_late(self):
        # the delta was captured BEFORE the fence ran: block_until_ready
        # between the delta and the sink cannot un-poison the measurement
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, hist):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.jit(step)(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    jax.block_until_ready(y)\n"
            "    hist.observe(dt)\n"
            "    return y\n"
        )
        assert codes(r) == ["JG015"]

    def test_true_negative_fenced_output(self):
        # the corrected idiom: fence THE CALL'S OWN output before the
        # second clock read (JG002's contract)
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, hist):\n"
            "    jf = jax.jit(step)\n"
            "    t0 = time.perf_counter()\n"
            "    y = jf(x)\n"
            "    jax.block_until_ready(y)\n"
            "    hist.observe(time.perf_counter() - t0)\n"
            "    return y\n"
        )
        assert codes(r) == []

    def test_true_negative_inline_asarray_fence(self):
        r = run(
            "import time\n"
            "import numpy as np\n"
            "import jax\n"
            "def f(step, x, hist):\n"
            "    t0 = time.perf_counter()\n"
            "    y = np.asarray(jax.jit(step)(x))\n"
            "    hist.observe(time.perf_counter() - t0)\n"
            "    return y\n"
        )
        assert codes(r) == []

    def test_true_negative_untraced_work(self):
        # the store's publish timing: fsync-bound host work, no device
        # async to fence — the delta is honest
        r = run(
            "import time\n"
            "def publish(write, staging, hist):\n"
            "    t0 = time.perf_counter()\n"
            "    write(staging)\n"
            "    hist.observe(time.perf_counter() - t0)\n"
        )
        assert codes(r) == []

    def test_true_negative_delta_into_plain_dict(self):
        # summaries/event lists are not scrape sinks; JG002/JG009 own the
        # general timed-region cases
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, out):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.jit(step)(x)\n"
            "    out['train_s'] = time.perf_counter() - t0\n"
            "    return y\n"
        )
        assert codes(r) == []

    def test_skips_test_modules(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def test_speed(step, x, hist):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.jit(step)(x)\n"
            "    hist.observe(time.perf_counter() - t0)\n"
            "    return y\n",
            path="tests/test_speed.py",
        )
        assert codes(r) == []

    def test_suppression_applies(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def f(step, x, hist):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.jit(step)(x)\n"
            "    hist.observe(time.perf_counter() - t0)  # jaxlint: disable=JG015\n"
            "    return y\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG015"]


# ===========================================================================
# JG016 — swappable engine attribute outside the lock/swap seam
# ===========================================================================

class TestSwapSeamUnguardedAccess:
    def test_true_positive_unlocked_read_of_swapped_attribute(self):
        # the reload-plane hazard: swap_engine rebinds self._engine under
        # the lock, but dispatch reads it bare — a flush cut from the old
        # engine can dispatch on the new one mid-swap
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._engine = engine\n"
            "    def swap_engine(self, engine):\n"
            "        with self._lock:\n"
            "            old, self._engine = self._engine, engine\n"
            "        return old\n"
            "    def dispatch(self, kind, rows):\n"
            "        return self._engine.dispatch(kind, rows)\n"
        )
        assert codes(r) == ["JG016"]
        assert "outside the lock" in r.active[0].message

    def test_true_positive_swap_seam_itself_unlocked(self):
        # the worst offender: the swap method rebinds without holding the
        # lock — every reader races the rebind (two findings: the read and
        # the store of the tuple assignment)
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._engine = engine\n"
            "    def swap_engine(self, engine):\n"
            "        old, self._engine = self._engine, engine\n"
            "        return old\n"
        )
        assert codes(r) == ["JG016", "JG016"]
        assert any("rebinds" in f.message for f in r.active)

    def test_true_positive_unlocked_write_in_other_method(self):
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._engine = engine\n"
            "    def swap_engine(self, engine):\n"
            "        with self._lock:\n"
            "            self._engine = engine\n"
            "    def reset(self):\n"
            "        self._engine = None\n"
        )
        assert codes(r) == ["JG016"]

    def test_true_negative_guarded_reads_and_snapshot(self):
        # the corrected idiom this repo's batcher uses: accessor under the
        # lock, worker snapshots to a local in the same critical section
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "        self._engine = engine\n"
            "    def swap_engine(self, engine):\n"
            "        with self._lock:\n"
            "            old, self._engine = self._engine, engine\n"
            "        return old\n"
            "    @property\n"
            "    def engine(self):\n"
            "        with self._lock:\n"
            "            return self._engine\n"
            "    def worker(self, kind, rows):\n"
            "        with self._cv:\n"
            "            engine = self._engine\n"
            "        return engine.dispatch(kind, rows)\n"
        )
        assert codes(r) == []

    def test_true_negative_init_and_counters_exempt(self):
        # __init__ is single-threaded by contract, and augmented counters
        # in the swap method are not swap targets — reading them bare
        # elsewhere is not this rule's business
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._engine = engine\n"
            "        self._swaps = 0\n"
            "    def swap_engine(self, engine):\n"
            "        with self._lock:\n"
            "            self._engine = engine\n"
            "            self._swaps += 1\n"
            "    def metrics(self):\n"
            "        return {'swaps': self._swaps}\n"
        )
        assert codes(r) == []

    def test_true_negative_class_without_swap_method(self):
        # no swap seam declared -> plain attribute use is not flagged
        r = run(
            "class Service:\n"
            "    def __init__(self, engine):\n"
            "        self._engine = engine\n"
            "    def dispatch(self, kind, rows):\n"
            "        return self._engine.dispatch(kind, rows)\n"
        )
        assert codes(r) == []

    def test_suppression_applies(self):
        r = run(
            "import threading\n"
            "class Batcher:\n"
            "    def __init__(self, engine):\n"
            "        self._lock = threading.Lock()\n"
            "        self._engine = engine\n"
            "    def swap_engine(self, engine):\n"
            "        with self._lock:\n"
            "            self._engine = engine\n"
            "    def peek(self):\n"
            "        return self._engine  # jaxlint: disable=JG016\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG016"]


# ===========================================================================
# JG017 — blocking network call without an explicit timeout
# ===========================================================================

class TestUnboundedNetworkCall:
    def test_true_positive_urlopen_without_timeout(self):
        # the fleet hazard: a health probe with no timeout wedges the
        # health loop behind the hung worker it was meant to eject
        r = run(
            "import urllib.request\n"
            "def probe(url):\n"
            "    with urllib.request.urlopen(url) as resp:\n"
            "        return resp.read()\n"
        )
        assert codes(r) == ["JG017"]
        assert "timeout" in r.active[0].message

    def test_true_positive_aliased_import_still_caught(self):
        r = run(
            "from urllib.request import urlopen as fetch\n"
            "def probe(url):\n"
            "    return fetch(url).read()\n"
        )
        assert codes(r) == ["JG017"]

    def test_true_positive_http_client_connection(self):
        r = run(
            "import http.client\n"
            "def proxy(host, port):\n"
            "    conn = http.client.HTTPConnection(host, port)\n"
            "    conn.request('GET', '/healthz')\n"
            "    return conn.getresponse().read()\n"
        )
        assert codes(r) == ["JG017"]

    def test_true_positive_socket_create_connection(self):
        r = run(
            "import socket\n"
            "def dial(addr):\n"
            "    return socket.create_connection(addr)\n"
        )
        assert codes(r) == ["JG017"]

    def test_true_negative_timeout_keyword(self):
        # the corrected idiom every fleet/router/watcher path uses
        r = run(
            "import http.client\n"
            "import urllib.request\n"
            "def probe(url, host):\n"
            "    with urllib.request.urlopen(url, timeout=2.0) as resp:\n"
            "        body = resp.read()\n"
            "    conn = http.client.HTTPConnection(host, 80, timeout=5.0)\n"
            "    conn.close()\n"
            "    return body\n"
        )
        assert codes(r) == []

    def test_true_negative_positional_timeout_slot(self):
        r = run(
            "import socket\n"
            "import urllib.request\n"
            "def dial(addr, url):\n"
            "    s = socket.create_connection(addr, 3.0)\n"
            "    return urllib.request.urlopen(url, None, 5.0), s\n"
        )
        assert codes(r) == []

    def test_true_negative_bind_shapes_not_flagged(self):
        # a bare socket() that binds/listens (free_port) never dials out
        r = run(
            "import socket\n"
            "def free_port():\n"
            "    with socket.socket() as s:\n"
            "        s.bind(('127.0.0.1', 0))\n"
            "        return s.getsockname()[1]\n"
        )
        assert codes(r) == []

    def test_true_negative_unrelated_local_helper(self):
        # a project-local urlopen helper is not the stdlib entry point
        r = run(
            "from myproj.http import urlopen\n"
            "def probe(url):\n"
            "    return urlopen(url)\n"
        )
        assert codes(r) == []

    def test_skips_test_modules(self):
        r = run(
            "import urllib.request\n"
            "def test_probe(url):\n"
            "    return urllib.request.urlopen(url)\n",
            path="tests/test_probe.py",
        )
        assert codes(r) == []

    def test_suppression_applies(self):
        r = run(
            "import urllib.request\n"
            "def probe(url):\n"
            "    return urllib.request.urlopen(url)  # jaxlint: disable=JG017\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG017"]


# ===========================================================================
# JG018 — updater state sharded unlike its paired params
# ===========================================================================

class TestShardedStateSpecMismatch:
    def test_true_positive_replicated_params_sharded_updater(self):
        # the update-sharding hazard: params replicated, RmsProp caches
        # sharded — every step reshards the full updater state
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(mesh, params, opt_state):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == ["JG018"]
        assert "reshard" in r.active[0].message

    def test_true_positive_role_from_assigned_name(self):
        # the placed expression is anonymous (optimizer.init(p)); the role
        # comes from the name the placement is assigned to
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def build(mesh, optimizer, p):\n"
            "    params = jax.device_put(p,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    opt_state = jax.device_put(optimizer.init(p),\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == ["JG018"]

    def test_true_positive_with_sharding_constraint_attr_roles(self):
        # constraint form inside a step fn; roles read off the attribute
        # names (TrainState.params / TrainState.opt_state)
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def step(mesh, state):\n"
            "    p = jax.lax.with_sharding_constraint(state.params,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    o = jax.lax.with_sharding_constraint(state.opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec(('data', 'model'))))\n"
            "    return p, o\n"
        )
        assert codes(r) == ["JG018"]

    def test_true_negative_matching_specs(self):
        # the corrected idiom: updater slots shard exactly like the params
        # they step
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(mesh, params, opt_state):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == []

    def test_true_negative_different_meshes_silent(self):
        # train vs serve meshes legitimately use different layouts
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(train_mesh, serve_mesh, params, opt_state):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(train_mesh, PartitionSpec()))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(serve_mesh, PartitionSpec('data')))\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == []

    def test_true_negative_non_literal_spec_silent(self):
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(mesh, params, opt_state, axis):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec(axis)))\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == []

    def test_true_negative_params_already_disagree(self):
        # no single param anchor to judge the updater against — silence,
        # not a guess
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(mesh, p1, p2, opt_state):\n"
            "    param_a = jax.device_put(p1,\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    param_b = jax.device_put(p2,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec('model')))\n"
            "    return param_a, param_b, opt_state\n"
        )
        assert codes(r) == []

    def test_skips_test_modules(self):
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def test_mismatch(mesh, params, opt_state):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))\n"
            "    return params, opt_state\n",
            path="tests/test_specs.py",
        )
        assert codes(r) == []

    def test_suppression_applies(self):
        r = run(
            "import jax\n"
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def place(mesh, params, opt_state):\n"
            "    params = jax.device_put(params,\n"
            "        NamedSharding(mesh, PartitionSpec()))\n"
            "    opt_state = jax.device_put(opt_state,\n"
            "        NamedSharding(mesh, PartitionSpec('data')))  # jaxlint: disable=JG018\n"
            "    return params, opt_state\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG018"]


# ===========================================================================
# JG019 — prefetch/data-pipeline callback reached from a timed region
# ===========================================================================

class TestPrefetchCallbackInTimedRegion:
    def test_true_positive_transform_in_timed_loop(self):
        # the streaming-pipeline hazard JG009 is structurally blind to:
        # the loop never CALLS the callback — the pipeline's refill does,
        # inside the timed region
        r = run(
            "import time\n"
            "import jax\n"
            "def log_row(batch):\n"
            "    jax.debug.print('batch {}', batch)\n"
            "    return batch\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def bench(inner, step):\n"
            "    it = make_prefetch(inner, transform=log_row)\n"
            "    t0 = time.perf_counter()\n"
            "    while it.has_next():\n"
            "        step(it.next())\n"
            "    return time.perf_counter() - t0\n"
        )
        assert "JG019" in codes(r)
        assert "prefetch refill" in r.active[0].message

    def test_true_positive_transitive_taint_and_passed_as_arg(self):
        # the callback reaches jax.debug.* through a helper (project-index
        # taint closure) and the pipeline is handed WHOLE to the timed
        # consumer (`run(exp, it)`) instead of method-called
        r = run(
            "import time\n"
            "import jax\n"
            "def helper(x):\n"
            "    jax.debug.print('x {}', x)\n"
            "    return x\n"
            "def transform(batch):\n"
            "    return helper(batch)\n"
            "def make_pipeline(inner, transform=None):\n"
            "    return inner\n"
            "def bench(run_fn, exp, inner):\n"
            "    it = make_pipeline(inner, transform=transform)\n"
            "    t0 = time.perf_counter()\n"
            "    run_fn(exp, it)\n"
            "    t1 = time.perf_counter()\n"
            "    return t1 - t0\n"
        )
        assert codes(r) == ["JG019"]

    def test_true_positive_for_loop_consumption(self):
        # the iterator protocol IS consumption: `for batch in it:` inside
        # the timed region must fire like it.next() does
        r = run(
            "import time\n"
            "import jax\n"
            "def log_row(batch):\n"
            "    jax.debug.print('b')\n"
            "    return batch\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def bench(inner, step):\n"
            "    it = make_prefetch(inner, transform=log_row)\n"
            "    t0 = time.perf_counter()\n"
            "    for batch in it:\n"
            "        step(batch)\n"
            "    return time.perf_counter() - t0\n"
        )
        assert "JG019" in codes(r)

    def test_true_positive_lambda_callback(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def bench(inner, step):\n"
            "    it = make_prefetch(\n"
            "        inner, transform=lambda b: jax.debug.print('b') or b)\n"
            "    t0 = time.perf_counter()\n"
            "    while it.has_next():\n"
            "        step(it.next())\n"
            "    return time.perf_counter() - t0\n"
        )
        assert "JG019" in codes(r)

    def test_true_negative_pure_transform(self):
        # numpy-only host-side transforms are the feature working as
        # intended — no host callback, no finding
        r = run(
            "import time\n"
            "import numpy as np\n"
            "def normalize(batch):\n"
            "    return batch\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def bench(inner, step):\n"
            "    it = make_prefetch(inner, transform=normalize)\n"
            "    t0 = time.perf_counter()\n"
            "    while it.has_next():\n"
            "        step(it.next())\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_true_negative_consumed_outside_timed_region(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def log_row(batch):\n"
            "    jax.debug.print('b')\n"
            "    return batch\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def build(inner, consume):\n"
            "    it = make_prefetch(inner, transform=log_row)\n"
            "    while it.has_next():\n"
            "        consume(it.next())\n"
            "    return time.perf_counter()\n"
        )
        assert codes(r) == []

    def test_true_negative_no_callback_argument(self):
        # the repo's own run() shape: a prefetch built from an iterator +
        # sharding only — nothing function-valued, nothing to taint
        r = run(
            "import time\n"
            "def make_prefetch(inner, depth=2, sharding=None):\n"
            "    return inner\n"
            "def bench(inner, step, sharding):\n"
            "    it = make_prefetch(inner, depth=2, sharding=sharding)\n"
            "    t0 = time.perf_counter()\n"
            "    while it.has_next():\n"
            "        step(it.next())\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_direct_callback_is_jg009_not_jg019(self):
        # the loop calling jax.debug.print itself is JG009's finding —
        # JG019 owns only the pipeline-construction indirection
        r = run(
            "import time\n"
            "import jax\n"
            "def bench(step, xs):\n"
            "    t0 = time.perf_counter()\n"
            "    for x in xs:\n"
            "        jax.debug.print('x {}', x)\n"
            "        step(x)\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == ["JG009"]

    def test_suppression_applies(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def log_row(batch):\n"
            "    jax.debug.print('b')\n"
            "    return batch\n"
            "def make_prefetch(inner, transform=None):\n"
            "    return inner\n"
            "def bench(inner, step):\n"
            "    it = make_prefetch(inner, transform=log_row)  # jaxlint: disable=JG019\n"
            "    t0 = time.perf_counter()\n"
            "    while it.has_next():\n"
            "        step(it.next())\n"
            "    return time.perf_counter() - t0\n"
        )
        assert "JG019" not in codes(r)
        assert "JG019" in [f.code for f in r.suppressed]


# ===========================================================================
# JG020 — synchronous host I/O on a timed train-step path
# ===========================================================================

class TestSyncHostIoOnStepPath:
    def test_true_positive_checkpoint_write_via_taint_closure(self):
        # the real measured stall: a publish helper (open/write/fsync)
        # called from the timed step loop — the I/O is two calls away
        # from the loop, visible only through the index's taint closure
        r = run(
            "import time\n"
            "import os\n"
            "import jax\n"
            "def publish(state, path):\n"
            "    with open(path, 'wb') as fh:\n"
            "        fh.write(state)\n"
            "        os.fsync(fh.fileno())\n"
            "def train(step_fn, xs):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    for x in xs:\n"
            "        out = step(x)\n"
            "        publish(out, 'ckpt.bin')\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == ["JG020"]
        assert "synchronous host I/O" in r.active[0].message

    def test_true_positive_direct_io_in_clock_reading_loop(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def train(step_fn, xs, log):\n"
            "    step = jax.jit(step_fn)\n"
            "    times = []\n"
            "    for x in xs:\n"
            "        t0 = time.perf_counter()\n"
            "        out = step(x)\n"
            "        open(log, 'a').write(str(out))\n"
            "        times.append(time.perf_counter() - t0)\n"
            "    return times\n"
        )
        assert "JG020" in codes(r)

    def test_true_positive_network_upload_through_helper(self):
        r = run(
            "import time\n"
            "import urllib.request\n"
            "import jax\n"
            "def upload(payload, url):\n"
            "    return urllib.request.urlopen(url, data=payload, timeout=5.0)\n"
            "def train(step_fn, xs, url):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    for x in xs:\n"
            "        upload(step(x), url)\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == ["JG020"]

    def test_true_negative_timed_publish_without_step_work(self):
        # the supervisor's _publish shape: a clock delta around the
        # store publish on purpose — fsync-bound and MEASURED AS SUCH,
        # no traced call in the window, not a step-path finding
        r = run(
            "import time\n"
            "import os\n"
            "def publish(state, path):\n"
            "    with open(path, 'wb') as fh:\n"
            "        fh.write(state)\n"
            "        os.fsync(fh.fileno())\n"
            "def timed_publish(state):\n"
            "    t0 = time.perf_counter()\n"
            "    publish(state, 'ckpt.bin')\n"
            "    return time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_true_negative_io_outside_the_timed_region(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def train(step_fn, xs, log):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    outs = [step(x) for x in xs]\n"
            "    dt = time.perf_counter() - t0\n"
            "    with open(log, 'w') as fh:\n"
            "        fh.write(str(dt))\n"
            "    return outs\n"
        )
        assert codes(r) == []

    def test_true_negative_pure_helper_is_not_io(self):
        r = run(
            "import time\n"
            "import numpy as np\n"
            "import jax\n"
            "def summarize(out):\n"
            "    return float(np.mean(out))\n"
            "def train(step_fn, xs):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    acc = [summarize(step(x)) for x in xs]\n"
            "    return acc, time.perf_counter() - t0\n"
        )
        assert codes(r) == []

    def test_skips_test_modules(self):
        r = run(
            "import time\n"
            "import jax\n"
            "def test_step_and_log(step_fn, xs):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    for x in xs:\n"
            "        open('log', 'a').write(str(step(x)))\n"
            "    assert time.perf_counter() - t0 < 1\n",
            path="tests/test_fx.py",
        )
        assert "JG020" not in codes(r)

    def test_suppression_applies(self):
        r = run(
            "import time\n"
            "import os\n"
            "import jax\n"
            "def publish(state, path):\n"
            "    with open(path, 'wb') as fh:\n"
            "        fh.write(state)\n"
            "        os.fsync(fh.fileno())\n"
            "def train(step_fn, xs):\n"
            "    step = jax.jit(step_fn)\n"
            "    t0 = time.perf_counter()\n"
            "    for x in xs:\n"
            "        publish(step(x), 'c.bin')  # jaxlint: disable=JG020\n"
            "    return time.perf_counter() - t0\n"
        )
        assert "JG020" not in codes(r)
        assert "JG020" in [f.code for f in r.suppressed]


# ===========================================================================
# JG021 — subprocess respawn loop with no cap and no backoff
# ===========================================================================

class TestUnboundedRespawnLoop:
    def test_true_positive_direct_popen_in_supervision_loop(self):
        # the fleet hazard: a worker that dies on every boot relaunches
        # as fast as the host can fork — a fork bomb with extra steps
        r = run(
            "import subprocess\n"
            "def supervise(cmd, stop):\n"
            "    proc = subprocess.Popen(cmd)\n"
            "    while not stop.is_set():\n"
            "        if proc.poll() is not None:\n"
            "            proc = subprocess.Popen(cmd)\n"
        )
        assert codes(r) == ["JG021"]
        assert "backoff" in r.active[0].message

    def test_true_positive_spawn_through_project_helper(self):
        # the realistic shape: the Popen lives in a relaunch helper, only
        # the index's spawn-taint closure connects it to the loop
        r = run(
            "import subprocess\n"
            "def relaunch(cmd, log):\n"
            "    return subprocess.Popen(cmd, stdout=log, stderr=log)\n"
            "def supervise(cmd, log, stop):\n"
            "    proc = relaunch(cmd, log)\n"
            "    while True:\n"
            "        if proc.poll() is not None:\n"
            "            proc = relaunch(cmd, log)\n"
        )
        assert codes(r) == ["JG021"]
        assert "relaunch" in r.active[0].message

    def test_true_positive_constructor_spawn(self):
        # a WorkerProcess-style wrapper class: the spawn sits in __init__
        r = run(
            "import subprocess\n"
            "class Worker:\n"
            "    def __init__(self, cmd):\n"
            "        self.proc = subprocess.Popen(cmd)\n"
            "def supervise(cmd, stop):\n"
            "    w = Worker(cmd)\n"
            "    while not stop.is_set():\n"
            "        if w.proc.poll() is not None:\n"
            "            w = Worker(cmd)\n"
        )
        assert codes(r) == ["JG021"]

    def test_true_positive_argless_popen_wait_is_not_a_pacer(self):
        # the canonical naive supervisor: p.wait() blocks on the child,
        # but a child that dies at boot returns it instantly — the loop
        # forks as fast as the host allows despite "waiting"
        r = run(
            "import subprocess\n"
            "def supervise(cmd):\n"
            "    while True:\n"
            "        p = subprocess.Popen(cmd)\n"
            "        p.wait()\n"
        )
        assert codes(r) == ["JG021"]

    def test_true_negative_backoff_sleep_paces_the_loop(self):
        # the corrected idiom: capped exponential backoff on the respawn
        r = run(
            "import subprocess\n"
            "import time\n"
            "def supervise(cmd, stop):\n"
            "    proc = subprocess.Popen(cmd)\n"
            "    failures = 0\n"
            "    while not stop.is_set():\n"
            "        if proc.poll() is not None:\n"
            "            failures += 1\n"
            "            time.sleep(min(30.0, 0.5 * 2 ** failures))\n"
            "            proc = subprocess.Popen(cmd)\n"
        )
        assert codes(r) == []

    def test_true_negative_event_wait_paces_the_loop(self):
        # the manager's supervise-loop shape: stop.wait(interval) is the
        # pacer even though it is not literally time.sleep
        r = run(
            "import subprocess\n"
            "def supervise(cmd, stop):\n"
            "    proc = subprocess.Popen(cmd)\n"
            "    while not stop.is_set():\n"
            "        if proc.poll() is not None:\n"
            "            proc = subprocess.Popen(cmd)\n"
            "        stop.wait(0.2)\n"
        )
        assert codes(r) == []

    def test_true_negative_attempt_capped_condition(self):
        # the resilience drill's relaunch-budget shape: the while
        # condition IS the attempt cap
        r = run(
            "import subprocess\n"
            "def drill(cmd, budget):\n"
            "    relaunches = 0\n"
            "    while relaunches <= budget:\n"
            "        rc = subprocess.run(cmd).returncode\n"
            "        if rc == 0:\n"
            "            break\n"
            "        relaunches += 1\n"
        )
        assert codes(r) == []

    def test_true_negative_for_loop_is_bounded(self):
        r = run(
            "import subprocess\n"
            "def retry(cmd):\n"
            "    for _ in range(5):\n"
            "        if subprocess.run(cmd).returncode == 0:\n"
            "            break\n"
        )
        assert codes(r) == []

    def test_true_negative_no_spawn_in_loop(self):
        r = run(
            "import subprocess\n"
            "def watch(proc, stop):\n"
            "    while not stop.is_set():\n"
            "        if proc.poll() is not None:\n"
            "            return proc.returncode\n"
        )
        assert codes(r) == []

    def test_skips_test_modules(self):
        r = run(
            "import subprocess\n"
            "def test_respawn(cmd, stop):\n"
            "    while not stop.is_set():\n"
            "        subprocess.Popen(cmd)\n",
            path="tests/test_respawn.py",
        )
        assert codes(r) == []

    def test_suppression_applies(self):
        r = run(
            "import subprocess\n"
            "def supervise(cmd, stop):\n"
            "    while not stop.is_set():\n"
            "        subprocess.Popen(cmd)  # jaxlint: disable=JG021\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG021"]


# ===========================================================================
# JG022 — unguarded cross-generation engine sharing (serving/mux seam)
# ===========================================================================

class TestCrossGenerationEngineSharing:
    def test_true_positive_direct_table_subscript(self):
        # the mux hazard: reading another generation's engine straight
        # out of the variant table — a concurrent residency-budget
        # demotion closes that engine's batcher mid-use
        r = run(
            "def warm_all(registry):\n"
            "    registry.variants['gen-12'].engine.warmup()\n"
        )
        assert codes(r) == ["JG022"]
        assert "registry.variants" in r.active[0].message
        assert "registry lock" in r.active[0].message

    def test_true_positive_iteration_over_table(self):
        # iterating the live table without the lock: membership itself
        # is concurrent state (adopt/demote rewrite it)
        r = run(
            "class MuxRegistry:\n"
            "    def kinds(self):\n"
            "        out = set()\n"
            "        for v in self._variants.values():\n"
            "            out.update(v.engine.kinds)\n"
            "        return out\n"
        )
        assert codes(r) == ["JG022"]

    def test_true_positive_wrong_object_lock(self):
        # holding SOME lock is not holding THE registry's lock: the
        # base-expression match is exact
        r = run(
            "def drain(self, other):\n"
            "    with self.lock:\n"
            "        return other.engines['a'].in_flight\n"
        )
        assert codes(r) == ["JG022"]

    def test_true_negative_access_under_registry_lock(self):
        # the corrected idiom the registry's accessors use
        r = run(
            "def engine_for(self, name):\n"
            "    with self.lock:\n"
            "        return self._variants[name].engine\n"
            "def route(registry, key):\n"
            "    with registry.lock:\n"
            "        return registry.variants[key].batcher\n"
        )
        assert codes(r) == []

    def test_true_negative_init_and_locked_helpers_exempt(self):
        # __init__ is single-threaded by contract; *_locked helpers run
        # with the caller already holding the lock (the registry's own
        # convention)
        r = run(
            "class MuxRegistry:\n"
            "    def __init__(self):\n"
            "        self._variants = {}\n"
            "    def _attach_locked(self, name, engine):\n"
            "        self._variants[name].engine = engine\n"
            "    def attach(self, name, engine):\n"
            "        with self.lock:\n"
            "            self._attach_locked(name, engine)\n"
        )
        assert codes(r) == []

    def test_true_negative_nested_def_does_not_inherit_the_lock(self):
        # a closure defined under the lock may run after the with block
        # exited (another thread, a callback) — it must take the lock
        # itself, and the rule must not bless it lexically
        r = run(
            "def snapshot(self):\n"
            "    with self.lock:\n"
            "        def render():\n"
            "            return dict(self._variants)\n"
            "        return render\n"
        )
        assert codes(r) == ["JG022"]

    def test_suppression_applies(self):
        r = run(
            "def peek(registry):\n"
            "    return len(registry.variants)  # jaxlint: disable=JG022\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG022"]


# ===========================================================================
# the project index (phase 1)
# ===========================================================================

class TestProjectIndex:
    def test_module_names_from_paths(self):
        from gan_deeplearning4j_tpu.analysis.project import module_name_for_path

        assert module_name_for_path("pkg/sub/mod.py") == "pkg.sub.mod"
        assert module_name_for_path("pkg/sub/__init__.py") == "pkg.sub"
        assert module_name_for_path("bench.py") == "bench"

    def test_summaries_record_tracing_donation_and_prng_params(self):
        from gan_deeplearning4j_tpu.analysis import engine
        from gan_deeplearning4j_tpu.analysis.project import build_index

        mod = engine.parse_module(
            "import jax\n"
            "import functools\n"
            "@functools.partial(jax.jit, donate_argnums=(0,))\n"
            "def step(state, batch, rng):\n"
            "    return state + batch\n",
            "pkg/steps.py",
        )
        idx = build_index([mod])
        s = idx.lookup("pkg.steps.step")
        assert s.traced and s.donates == (0,)
        assert s.prng_params == ("rng",)
        assert s.params == ("state", "batch", "rng")

    def test_relative_imports_absolutize(self):
        from gan_deeplearning4j_tpu.analysis import engine
        from gan_deeplearning4j_tpu.analysis.project import build_index

        pkg_init = engine.parse_module(
            "from .steps import step\n", "pkg/__init__.py")
        steps = engine.parse_module(
            "def step(s):\n    return s\n", "pkg/steps.py")
        idx = build_index([pkg_init, steps])
        assert idx.modules["pkg"].imports["step"] == "pkg.steps.step"
        # one re-export hop: `from pkg import step` resolves to pkg.steps.step
        user = engine.parse_module("from pkg import step\n", "app.py")
        idx2 = build_index([pkg_init, steps, user])
        s = idx2.resolve_function(user, "step")
        assert s is not None and s.fq == "pkg.steps.step"


# ===========================================================================
# engine mechanics: suppression, baseline, fingerprints, CLI
# ===========================================================================

SUPPRESSED_SRC = (
    "import jax\n"
    "def f(key, b):\n"
    "    a = jax.random.uniform(key, (b,))\n"
    "    c = jax.random.uniform(key, (b,))  # jaxlint: disable=JG001\n"
    "    return a, c\n"
)


class TestSuppression:
    def test_trailing_comment_suppresses_and_is_counted(self):
        r = run(SUPPRESSED_SRC)
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG001"]

    def test_wrong_code_does_not_suppress(self):
        r = run(SUPPRESSED_SRC.replace("disable=JG001", "disable=JG003"))
        assert codes(r) == ["JG001"]

    def test_disable_all(self):
        r = run(SUPPRESSED_SRC.replace("disable=JG001", "disable=all"))
        assert codes(r) == []
        assert len(r.suppressed) == 1

    def test_multiline_statement_suppressed_from_any_span_line(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.uniform(\n"
            "        key, (b,)  # jaxlint: disable=JG001\n"
            "    )\n"
            "    return a, c\n"
        )
        assert codes(r) == []

    def test_suppression_inside_string_literal_is_ignored(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.uniform(key, (b,))\n"
            "    return a, c, 'jaxlint: disable=JG001'\n"
        )
        assert codes(r) == ["JG001"]

    def test_multiple_codes_on_one_line(self):
        # one line can violate two rules; one comment may name both
        src = (
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    assert jax.random.uniform(key, (b,)).size  # jaxlint: disable=JG001,JG003\n"
            "    return a\n"
        )
        r = run(src)
        assert codes(r) == []
        assert sorted(f.code for f in r.suppressed) == ["JG001", "JG003"]
        # naming only one of the two leaves the other active
        r2 = run(src.replace("disable=JG001,JG003", "disable=JG001"))
        assert codes(r2) == ["JG003"]

    def test_all_wildcard_covers_multiple_codes(self):
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    assert jax.random.uniform(key, (b,)).size  # jaxlint: disable=all\n"
            "    return a\n"
        )
        assert codes(r) == []
        assert len(r.suppressed) == 2

    def test_suppression_on_backslash_continuation(self):
        # the comment can only live on the LAST physical line of a
        # backslash-continued statement (comments after `\` are illegal);
        # the span rule must still honor it
        r = run(
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.\\\n"
            "        uniform(key, (b,))  # jaxlint: disable=JG001\n"
            "    return a, c\n"
        )
        assert codes(r) == []
        assert [f.code for f in r.suppressed] == ["JG001"]

    def test_unknown_rule_code_warns_not_silent(self):
        # a typo'd suppression must not pass silently: the finding stays
        # active AND the engine reports the bogus code
        r = run(SUPPRESSED_SRC.replace("disable=JG001", "disable=JG101"))
        assert codes(r) == ["JG001"]
        assert len(r.warnings) == 1
        assert "JG101" in r.warnings[0] and "unknown rule code" in r.warnings[0]

    def test_known_codes_and_all_do_not_warn(self):
        assert run(SUPPRESSED_SRC).warnings == []
        assert run(
            SUPPRESSED_SRC.replace("disable=JG001", "disable=all")
        ).warnings == []


class TestBaseline:
    TP = TestBareAssert  # convenience

    def test_baselined_finding_is_not_active(self):
        src = "def f(x):\n    assert x\n"
        r = run(src, path="fx/prod.py")
        (f,) = r.active
        baseline = [{"fingerprint": f.fingerprint, "rule": "JG003",
                     "path": f.path, "justification": "known, tracked"}]
        r2 = run(src, path="fx/prod.py", baseline=baseline)
        assert r2.active == [] and len(r2.baselined) == 1
        assert r2.stale_baseline == []

    def test_stale_baseline_entry_is_reported(self):
        baseline = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
                     "path": "fx/prod.py", "justification": "was fixed"}]
        r = run("def f(x):\n    return x\n", path="fx/prod.py",
                baseline=baseline)
        assert r.active == []
        assert len(r.stale_baseline) == 1

    def test_out_of_scope_entries_are_not_stale(self):
        """A scoped run (--changed-only, path subset, --rules) must not call
        entries stale when their file was not analyzed or their rule did not
        run — and --prune-baseline must not delete them."""
        src = "def f(x):\n    return x\n"
        other_file = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
                       "path": "elsewhere/prod.py",
                       "justification": "lives in a file this run skipped"}]
        r = run(src, path="fx/prod.py", baseline=other_file)
        assert r.stale_baseline == [] and r.gate_ok
        from gan_deeplearning4j_tpu.analysis.rules import RULES_BY_CODE

        other_rule = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG001",
                       "path": "fx/prod.py",
                       "justification": "its rule is filtered out"}]
        r2 = run(src, path="fx/prod.py", baseline=other_rule,
                 rules=[RULES_BY_CODE["JG003"]])
        assert r2.stale_baseline == [] and r2.gate_ok
        # same path, rule DID run, fingerprint unmatched -> genuinely stale
        r3 = run(src, path="fx/prod.py", baseline=other_rule)
        assert len(r3.stale_baseline) == 1 and not r3.gate_ok

    def test_changed_files_from_repo_subdirectory(self, tmp_path):
        """Modified tracked files must be seen when the analyzer runs from a
        subdirectory (git diff emits toplevel-relative paths, ls-files
        cwd-relative ones — the subdir run must normalize both)."""
        import shutil

        from gan_deeplearning4j_tpu.analysis import changed_files

        if shutil.which("git") is None:  # pragma: no cover
            pytest.skip("no git in container")
        env = {**os.environ,
               "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        subprocess.run(["git", "-C", str(tmp_path), "init", "-q"],
                       check=True, env=env)
        sub = tmp_path / "pkg"
        sub.mkdir()
        tracked = sub / "mod.py"
        tracked.write_text("def f(x):\n    return x\n")
        subprocess.run(["git", "-C", str(tmp_path), "add", "-A"],
                       check=True, env=env)
        subprocess.run(["git", "-C", str(tmp_path), "commit", "-qm", "seed"],
                       check=True, capture_output=True, env=env)
        tracked.write_text("def f(x):\n    return x + 1\n")
        (sub / "new.py").write_text("def g():\n    return 1\n")
        got = changed_files(root=str(sub))
        assert got == ["mod.py", "new.py"]
        # from the toplevel the same files appear with their prefix
        assert changed_files(root=str(tmp_path)) == [
            "pkg/mod.py", "pkg/new.py"]

    def test_fingerprint_survives_line_drift_but_not_edits(self):
        src = "def f(x):\n    assert x\n"
        f1 = run(src, path="fx/prod.py").active[0]
        f2 = run("# a new leading comment\n\n" + src,
                 path="fx/prod.py").active[0]
        assert f1.fingerprint == f2.fingerprint  # moved, same content
        f3 = run(src.replace("assert x", "assert x, 'msg'"),
                 path="fx/prod.py").active[0]
        assert f3.fingerprint != f1.fingerprint  # line content changed

    def test_baseline_without_justification_is_refused(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"entries": [
            {"fingerprint": "abc", "rule": "JG003", "path": "x.py"}
        ]}))
        with pytest.raises(ValueError, match="justification"):
            load_baseline(str(p))

    def test_checked_in_baseline_loads_and_every_entry_is_justified(self):
        for e in load_baseline(DEFAULT_BASELINE_PATH):
            assert str(e.get("justification", "")).strip()
            assert "TODO" not in e.get("justification", "")


class TestParseErrors:
    def test_unparseable_file_is_a_finding_not_a_crash(self):
        r = run("def broken(:\n")
        assert codes(r) == ["JG000"]


# ===========================================================================
# autofix: --fix rewrites, --fix-suppress insertion, idempotency
# ===========================================================================

class TestAutofix:
    DIRTY = (
        "import jax.numpy as jnp\n"
        "MAX = 10\n"
        "def emit(line, x, i, v):\n"
        "    assert len(line) < MAX, 'oversize'\n"
        "    x.at[i].set(v)\n"
        "    return x\n"
    )

    def _fix(self, tmp_path, src, suppress=False, justification=None):
        from gan_deeplearning4j_tpu.analysis import fix as fix_mod

        p = tmp_path / "prod.py"
        p.write_text(src)
        report = analyze_paths([str(p)], baseline=None, root=str(tmp_path))
        result = fix_mod.apply_fixes(
            report, root=str(tmp_path), suppress=suppress,
            justification=justification,
        )
        return p, result

    def test_fix_rewrites_assert_and_at_update(self, tmp_path):
        p, result = self._fix(tmp_path, self.DIRTY)
        assert result.rewritten == 2 and result.suppressed == 0
        fixed = p.read_text()
        assert "assert" not in fixed
        assert "raise AssertionError('oversize')" in fixed
        assert "x = x.at[i].set(v)" in fixed
        # the rewritten file is clean AND semantically parseable
        import ast as _ast
        _ast.parse(fixed)
        assert analyze_paths([str(p)], root=str(tmp_path)).active == []

    def test_fix_is_idempotent(self, tmp_path):
        from gan_deeplearning4j_tpu.analysis import fix as fix_mod

        p, _ = self._fix(tmp_path, self.DIRTY)
        once = p.read_text()
        report = analyze_paths([str(p)], root=str(tmp_path))
        result = fix_mod.apply_fixes(report, root=str(tmp_path))
        assert result.rewritten == 0 and result.files == []
        assert p.read_text() == once

    def test_fix_skips_non_starting_statements(self, tmp_path):
        # `if x: assert y` cannot be mechanically rewritten in place
        p, result = self._fix(tmp_path,
                              "def f(x, y):\n"
                              "    if x: assert y\n"
                              "    return x\n")
        assert result.rewritten == 0
        assert len(result.skipped) == 1 and "JG003" in result.skipped[0]
        assert "assert y" in p.read_text()

    def test_fix_suppress_requires_justification(self, tmp_path):
        from gan_deeplearning4j_tpu.analysis import fix as fix_mod

        with pytest.raises(ValueError, match="justification"):
            fix_mod.apply_fixes(
                analyze_source("def f(x):\n    assert x\n", "p.py"),
                suppress=True,
            )

    def test_fix_suppress_inserts_and_is_idempotent(self, tmp_path):
        p, result = self._fix(
            tmp_path, self.DIRTY, suppress=True,
            justification="fixture exercises the hazard on purpose",
        )
        assert result.suppressed == 2
        text = p.read_text()
        assert text.count("jaxlint: disable=") == 2
        assert "-- fixture exercises the hazard on purpose" in text
        report = analyze_paths([str(p)], root=str(tmp_path))
        assert report.active == [] and len(report.suppressed) == 2
        # second pass: nothing left to suppress, file unchanged
        from gan_deeplearning4j_tpu.analysis import fix as fix_mod

        again = fix_mod.apply_fixes(
            report, root=str(tmp_path), suppress=True, justification="again")
        assert again.suppressed == 0
        assert p.read_text() == text

    def test_fix_suppress_lands_after_backslash_continuation(self, tmp_path):
        p, result = self._fix(
            tmp_path,
            "import jax\n"
            "def f(key, b):\n"
            "    a = jax.random.uniform(key, (b,))\n"
            "    c = jax.random.\\\n"
            "        uniform(key, (b,))\n"
            "    return a, c\n",
            suppress=True, justification="test fixture",
        )
        assert result.suppressed == 1
        lines = p.read_text().splitlines()
        assert lines[3].rstrip().endswith("\\")  # untouched continuation
        assert "jaxlint: disable=JG001" in lines[4]
        assert analyze_paths([str(p)], root=str(tmp_path)).active == []


class TestCli:
    def _cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis", *args],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_clean_file_exits_zero(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("import jax\n\n\ndef f(x):\n    return x\n")
        proc = self._cli(str(p))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_finding_exits_one_and_reports_path_line(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline")
        assert proc.returncode == 1
        assert "JG003" in proc.stdout and ":2:" in proc.stdout

    def test_json_format(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline", "--format", "json")
        data = json.loads(proc.stdout)
        assert data["clean"] is False
        assert data["active"][0]["code"] == "JG003"
        assert data["active"][0]["fingerprint"]

    def test_rule_filter(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline", "--rules", "JG001")
        assert proc.returncode == 0

    def test_bogus_path_fails_loudly(self, tmp_path):
        # a typo'd CI target must not shrink the gate to whatever resolved
        proc = self._cli(str(tmp_path / "no_such_dir"), "--no-baseline")
        assert proc.returncode == 2
        assert "neither a directory nor an existing .py file" in proc.stderr

    def test_sarif_format(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = self._cli(str(p), "--no-baseline", "--format", "sarif")
        assert proc.returncode == 1
        data = json.loads(proc.stdout)
        assert data["version"] == "2.1.0"
        run0 = data["runs"][0]
        assert run0["tool"]["driver"]["name"] == "jaxlint"
        assert {r["id"] for r in run0["tool"]["driver"]["rules"]} == {
            r.code for r in RULES}
        (res,) = run0["results"]
        assert res["ruleId"] == "JG003" and res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] == 2
        assert res["partialFingerprints"]["jaxlint/v1"]

    def test_stale_baseline_entry_fails_the_gate(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("def f(x):\n    return x\n")
        bl = tmp_path / "bl.json"
        # no path metadata -> conservatively in-scope for any run
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
             "justification": "fixed long ago"}
        ]}))
        proc = self._cli(str(p), "--baseline", str(bl))
        assert proc.returncode == 1
        assert "stale baseline entry" in proc.stdout

    def test_prune_baseline_drops_stale_and_clears_the_gate(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("def f(x):\n    return x\n")
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
             "justification": "fixed long ago"}
        ]}))
        proc = self._cli(str(p), "--baseline", str(bl), "--prune-baseline")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "pruned 1 stale baseline entry" in proc.stderr
        assert json.loads(bl.read_text())["entries"] == []
        # gate is green afterwards without the flag
        proc2 = self._cli(str(p), "--baseline", str(bl))
        assert proc2.returncode == 0

    def test_fix_suppress_without_justification_is_a_usage_error(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n")
        proc = self._cli(str(p), "--no-baseline", "--fix-suppress")
        assert proc.returncode == 2
        assert "justification" in proc.stderr

    def test_changed_only_in_a_git_repo(self, tmp_path):
        import shutil

        if shutil.which("git") is None:  # pragma: no cover
            pytest.skip("no git in container")
        env = {**os.environ,
               "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}

        def git(*args):
            subprocess.run(["git", "-C", str(tmp_path), *args],
                           check=True, capture_output=True, env=env)

        git("init", "-q")
        committed = tmp_path / "committed.py"
        committed.write_text("def f(x):\n    assert x\n    return x\n")
        git("add", "-A")
        git("commit", "-qm", "seed")
        # untracked dirty file + committed dirty file: --changed-only must
        # see ONLY the untracked one
        fresh = tmp_path / "fresh.py"
        fresh.write_text("def g(x):\n    assert x\n    return x\n")
        proc = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
             ".", "--no-baseline", "--changed-only"],
            capture_output=True, text=True, cwd=str(tmp_path),
            env={**env, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1
        assert "fresh.py" in proc.stdout
        assert "committed.py" not in proc.stdout
        # with no changes at all: clean exit, explicit notice
        fresh.unlink()
        proc2 = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
             ".", "--no-baseline", "--changed-only"],
            capture_output=True, text=True, cwd=str(tmp_path),
            env={**env, "PYTHONPATH": REPO},
        )
        assert proc2.returncode == 0
        assert "no changed .py files" in proc2.stderr


# ===========================================================================
# the tier-1 gate: the tree this repo ships is clean
# ===========================================================================

class TestTreeIsClean:
    TARGETS = ["gan_deeplearning4j_tpu", "bench.py", "scripts"]

    def test_tree_is_clean(self):
        """The acceptance invariant: the analyzer over the whole package +
        bench.py + scripts reports nothing that is not baselined-with-
        justification. A new violation fails tier-1 with the finding text."""
        rep = analyze_paths(self.TARGETS, baseline=load_baseline(), root=REPO)
        assert rep.active == [], "\n" + "\n".join(
            f.render() for f in rep.active)
        assert rep.stale_baseline == [], rep.stale_baseline

    def test_analyzer_package_is_clean_by_itself(self):
        """The tier-1 SELF-check: the analyzer analyzes its own package.
        jaxlint's own code is non-test production Python — it must hold the
        standards it enforces (and this catches a rule crashing on the
        analyzer's own idioms, which the whole-tree gate would attribute
        elsewhere)."""
        rep = analyze_paths(["gan_deeplearning4j_tpu/analysis"],
                            baseline=load_baseline(), root=REPO)
        assert rep.active == [], "\n" + "\n".join(
            f.render() for f in rep.active)

    def test_rules_all_have_fixture_coverage(self):
        # every registered rule code appears in a TP fixture test above —
        # guards against registering a rule nobody proves fires
        here = open(__file__, encoding="utf-8").read()
        for rule in RULES:
            assert f'["{rule.code}"]' in here, (
                f"rule {rule.code} has no true-positive fixture asserting "
                f"it fires")

    def test_the_analyzer_is_jax_free(self):
        # must import (and run) with no jax available: parent-side tooling
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.modules['jax'] = None\n"
             "import gan_deeplearning4j_tpu.analysis as a\n"
             "r = a.analyze_source('def f(x):\\n    assert x\\n', 'p.py')\n"
             "print(len(r.active))"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == "1"


# ===========================================================================
# JG023 — unknown metric in alert rule
# ===========================================================================

class TestUnknownMetricInAlertRule:
    def test_true_positive_typo_metric(self):
        # the silent failure mode: the family is "fleet_member_up", the
        # rule says "fleet_member_upp" — it evaluates nothing forever
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def setup():\n"
            "    get_registry().gauge('fleet_member_up', 'x')\n"
            "    return AlertRule(name='down', kind='threshold',\n"
            "                     metric='fleet_member_upp',\n"
            "                     op='<', bound=1.0)\n"
        )
        assert codes(r) == ["JG023"]
        assert "fleet_member_upp" in r.active[0].message

    def test_true_positive_positional_metric(self):
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def setup():\n"
            "    get_registry().counter('requests_total', 'x')\n"
            "    return AlertRule('r', 'absence', 'request_total')\n"
        )
        assert codes(r) == ["JG023"]

    def test_true_positive_cross_module_still_checked(self):
        # the family lives in another module of the same analysis run —
        # the known set is project-wide, so the typo still surfaces
        from gan_deeplearning4j_tpu.analysis import analyze_sources

        report = analyze_sources({
            "pkg/metrics.py": (
                "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
                "def families():\n"
                "    get_registry().gauge('fleet_pressure_real', 'x')\n"
            ),
            "pkg/rules.py": (
                "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
                "def rules():\n"
                "    return [AlertRule(name='p', kind='anomaly',\n"
                "                      metric='fleet_pressure_reel')]\n"
            ),
        })
        assert [f.code for f in report.active] == ["JG023"]
        assert report.active[0].path == "pkg/rules.py"

    def test_true_negative_exact_family(self):
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def setup():\n"
            "    get_registry().gauge('fleet_member_up', 'x')\n"
            "    return AlertRule(name='down', kind='threshold',\n"
            "                     metric='fleet_member_up',\n"
            "                     op='<', bound=1.0)\n"
        )
        assert codes(r) == []

    def test_true_negative_fstring_family_pattern(self):
        # the SLOTracker shape: the family name is prefix-scoped at
        # construction; any rule matching the pattern resolves
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def setup(prefix):\n"
            "    get_registry().gauge(f'{prefix}_slo_burn_rate', 'x')\n"
            "    return AlertRule(name='b', kind='burn',\n"
            "                     metric='mux_slo_burn_rate')\n"
        )
        assert codes(r) == []

    def test_true_negative_module_constant_family(self):
        # aggregate.MEMBER_UP-style declaration: a module-level ALL_CAPS
        # string constant that looks like a metric name counts
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "MEMBER_UP = 'fleet_member_up'\n"
            "def setup():\n"
            "    return AlertRule(name='down', kind='threshold',\n"
            "                     metric='fleet_member_up',\n"
            "                     op='<', bound=1.0)\n"
        )
        assert codes(r) == []

    def test_true_negative_non_literal_metric(self):
        # computed names are out of scope: silence, not a guess
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "def setup(family):\n"
            "    return AlertRule(name='x', kind='absence', metric=family)\n"
        )
        assert codes(r) == []

    def test_true_negative_unrelated_call_named_alertrule_elsewhere(self):
        # no AlertRule constructions at all: the known-family scan never
        # even runs
        r = run(
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def setup():\n"
            "    get_registry().gauge('g_x', 'x')\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG024 — unguarded shared mutable state
# ===========================================================================

class TestUnguardedSharedMutableState:
    def test_true_positive_unguarded_read_escape(self):
        # the healthz shape: the loop thread mutates counts under the lock,
        # the public snapshot reads it bare — a torn dict walk waiting
        r = run(
            "import threading\n"
            "class Sampler:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "        self._thread = None\n"
            "    def start(self):\n"
            "        self._thread = threading.Thread(target=self._loop,\n"
            "                                        daemon=True)\n"
            "        self._thread.start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.counts['a'] = self.counts.get('a', 0) + 1\n"
            "        with self._lock:\n"
            "            self.counts['b'] = 1\n"
            "    def snapshot(self):\n"
            "        return dict(self.counts)\n"
        )
        assert codes(r) == ["JG024"]
        msg = r.active[0].message
        assert "snapshot" in msg and "counts" in msg and "_lock" in msg

    def test_true_positive_unguarded_store_escape(self):
        # the reload shape: the rebind escapes the lock the readers use
        r = run(
            "import threading\n"
            "class Holder:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.state = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.state['ticks'] = self.state.get('ticks', 0) + 1\n"
            "    def reset(self):\n"
            "        self.state = {}\n"
        )
        assert codes(r) == ["JG024"]
        assert "mutates" in r.active[0].message

    def test_true_negative_all_accesses_guarded(self):
        r = run(
            "import threading\n"
            "class Sampler:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.counts['a'] = self.counts.get('a', 0) + 1\n"
            "        with self._lock:\n"
            "            self.counts['b'] = 1\n"
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return dict(self.counts)\n"
        )
        assert codes(r) == []

    def test_true_negative_never_locked_attribute(self):
        # an Event-style atomic flag: no lock discipline exists, so there
        # is nothing to escape — flagging it would just demand ceremony
        r = run(
            "import threading\n"
            "class Flag:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        self.hits += 1\n"
            "    def read(self):\n"
            "        return self.hits\n"
        )
        assert codes(r) == []

    def test_true_negative_no_threads_spawned(self):
        # same lock/escape shape, but nothing concurrent ever runs
        r = run(
            "import threading\n"
            "class Seq:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "    def tick(self):\n"
            "        with self._lock:\n"
            "            self.counts['a'] = 1\n"
            "        with self._lock:\n"
            "            self.counts['b'] = 2\n"
            "    def snapshot(self):\n"
            "        return dict(self.counts)\n"
        )
        assert codes(r) == []

    def test_true_negative_read_only_outside_init(self):
        # config, not state: assigned once at construction, only read after
        r = run(
            "import threading\n"
            "class Cfg:\n"
            "    def __init__(self, n):\n"
            "        self._lock = threading.Lock()\n"
            "        self.limit = n\n"
            "        self.seen = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.seen.append(self.limit)\n"
            "        with self._lock:\n"
            "            self.seen.append(0)\n"
            "    def read(self):\n"
            "        return self.limit\n"
        )
        assert codes(r) == []

    def test_true_negative_caller_holds_the_lock_convention(self):
        # a private helper mutates bare, but every in-class call site holds
        # the lock — call-site guard propagation must see through it
        r = run(
            "import threading\n"
            "class Conv:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._bump('a')\n"
            "    def record(self):\n"
            "        with self._lock:\n"
            "            self._bump('b')\n"
            "    def _bump(self, k):\n"
            "        self.counts[k] = self.counts.get(k, 0) + 1\n"
        )
        assert codes(r) == []

    def test_true_negative_http_handler_instances_are_per_request(self):
        # BaseHTTPRequestHandler subclasses get a fresh instance per
        # request: self attrs are not shared across threads
        r = run(
            "import threading\n"
            "from http.server import BaseHTTPRequestHandler\n"
            "class H(BaseHTTPRequestHandler):\n"
            "    def do_GET(self):\n"
            "        self.hits = getattr(self, 'hits', 0) + 1\n"
            "        self.wfile.write(b'ok')\n"
        )
        assert codes(r) == []

    def test_suppression_on_the_escape_line_suppresses_exactly_it(self):
        # satellite: the disable comment must silence the one access it
        # annotates, not the attribute — a second escape still fires
        src = (
            "import threading\n"
            "class Sampler:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.counts['a'] = self.counts.get('a', 0) + 1\n"
            "        with self._lock:\n"
            "            self.counts['b'] = 1\n"
            "    def snapshot(self):\n"
            "        return dict(self.counts)  # jaxlint: disable=JG024 (read is advisory)\n"
            "    def drain(self):\n"
            "        return self.counts.pop('a', None)\n"
        )
        r = run(src)
        assert codes(r) == ["JG024"]
        assert "drain" in r.active[0].message
        assert len(r.suppressed) == 1
        assert "snapshot" in r.suppressed[0].message


# ===========================================================================
# JG025 — lock-order inversion
# ===========================================================================

class TestLockOrderInversion:
    def test_true_positive_opposite_nesting(self):
        r = run(
            "import threading\n"
            "class Pair:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                return 1\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                return 2\n"
        )
        assert codes(r) == ["JG025"]
        msg = r.active[0].message
        assert "Pair._a" in msg and "Pair._b" in msg and "deadlock" in msg

    def test_true_positive_inversion_through_call_hop(self):
        # one edge is only visible through a resolved same-class call:
        # one() holds _a and calls _helper(), which takes _b
        r = run(
            "import threading\n"
            "class Pair:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            self._helper()\n"
            "    def _helper(self):\n"
            "        with self._b:\n"
            "            return 1\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                return 2\n"
        )
        assert codes(r) == ["JG025"]

    def test_true_positive_module_level_locks(self):
        r = run(
            "import threading\n"
            "IO_LOCK = threading.Lock()\n"
            "NET_LOCK = threading.Lock()\n"
            "def one():\n"
            "    with IO_LOCK:\n"
            "        with NET_LOCK:\n"
            "            return 1\n"
            "def two():\n"
            "    with NET_LOCK:\n"
            "        with IO_LOCK:\n"
            "            return 2\n"
        )
        assert codes(r) == ["JG025"]

    def test_true_negative_consistent_global_order(self):
        r = run(
            "import threading\n"
            "class Pair:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                return 1\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                return 2\n"
        )
        assert codes(r) == []

    def test_true_negative_reentrant_same_lock(self):
        # RLock re-entrancy is not an inversion: a self-edge is no cycle
        r = run(
            "import threading\n"
            "class Re:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                return 1\n"
        )
        assert codes(r) == []

    def test_true_negative_condition_over_lock_is_an_alias(self):
        # Condition(self._lock) IS self._lock: nesting them is re-entry
        # (by design: notify under the same lock wait released), not an
        # A->B edge
        r = run(
            "import threading\n"
            "class CV:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "    def put(self):\n"
            "        with self._lock:\n"
            "            with self._cv:\n"
            "                self._cv.notify()\n"
        )
        assert codes(r) == []

    def test_true_negative_unnested_acquisitions(self):
        r = run(
            "import threading\n"
            "class Seq:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            pass\n"
            "        with self._b:\n"
            "            pass\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            pass\n"
            "        with self._a:\n"
            "            pass\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG026 — blocking call under a lock
# ===========================================================================

class TestBlockingCallUnderLock:
    def test_true_positive_sleep_under_lock(self):
        r = run(
            "import threading\n"
            "import time\n"
            "class Poller:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.state = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            time.sleep(0.5)\n"
            "            self.state['t'] = 1\n"
        )
        assert codes(r) == ["JG026"]
        msg = r.active[0].message
        assert "time.sleep" in msg and "_lock" in msg

    def test_true_positive_network_call_under_lock(self):
        # bounded (JG017-clean) but still parked under the lock every
        # request thread turns around on
        r = run(
            "import threading\n"
            "import urllib.request\n"
            "class Prober:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            urllib.request.urlopen('http://x/healthz', timeout=2)\n"
        )
        assert codes(r) == ["JG026"]

    def test_true_positive_join_through_call_hop(self):
        # the deadlock shape: stop() holds the lock and joins the worker
        # (via a helper) while the worker may be parked on the same lock
        r = run(
            "import threading\n"
            "class Mgr:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._thread = threading.Thread(target=self._loop,\n"
            "                                        daemon=True)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "    def stop(self):\n"
            "        with self._lock:\n"
            "            self._reap()\n"
            "    def _reap(self):\n"
            "        self._thread.join(timeout=5.0)\n"
        )
        assert codes(r) == ["JG026"]
        assert "_reap" in r.active[0].message

    def test_true_negative_snapshot_then_block_outside(self):
        # the correct idiom the fleet manager uses: copy under the lock,
        # wait outside it
        r = run(
            "import threading\n"
            "import time\n"
            "class Poller:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.state = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            snap = dict(self.state)\n"
            "        time.sleep(0.5)\n"
            "        return snap\n"
        )
        assert codes(r) == []

    def test_true_negative_no_threads(self):
        # single-threaded blocking under a lock is just I/O — nothing
        # contends
        r = run(
            "import threading\n"
            "import time\n"
            "class Seq:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            time.sleep(0.1)\n"
        )
        assert codes(r) == []

    def test_true_negative_condition_wait_releases_the_lock(self):
        r = run(
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cv = threading.Condition(self._lock)\n"
            "        self.items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait(timeout=1.0)\n"
            "            self.items.append(1)\n"
        )
        assert codes(r) == []

    def test_true_negative_str_join_is_not_thread_join(self):
        r = run(
            "import threading\n"
            "class Fmt:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.parts = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.parts.append(', '.join(['a', 'b']))\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG027 — leaked paired resource (lifecycle index)
# ===========================================================================

class TestLeakedPairedResource:
    def test_true_positive_early_exit(self):
        # the PR 8 router shape: a token taken, then a guard clause
        # returns without giving it back
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def work(items):\n"
            "    LOCK.acquire()\n"
            "    if not items:\n"
            "        return None\n"
            "    LOCK.release()\n"
            "    return items\n"
        )
        assert codes(r) == ["JG027"]
        assert "early exit" in r.active[0].message
        assert r.active[0].line == 4  # anchored at the open, not the exit

    def test_true_positive_exception_path(self):
        # the PR 6 device-capture shape: a raise-capable call sits in the
        # unprotected gap between acquire and release
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def snap(load, path):\n"
            "    LOCK.acquire()\n"
            "    data = load(path)\n"
            "    LOCK.release()\n"
            "    return data\n"
        )
        assert codes(r) == ["JG027"]
        assert "exception" in r.active[0].message

    def test_true_positive_partial_branch_close(self):
        # closed on one arm only, then control falls off the end
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def partial(flag):\n"
            "    LOCK.acquire()\n"
            "    if flag:\n"
            "        LOCK.release()\n"
        )
        assert codes(r) == ["JG027"]

    def test_true_positive_inflight_counter(self):
        # the PR 4 ledger shape: += opens a reservation the -= must
        # release on every path out
        r = run(
            "class Ledger:\n"
            "    def __init__(self):\n"
            "        self.inflight = 0\n"
            "    def submit(self, item):\n"
            "        self.inflight += 1\n"
            "        if item is None:\n"
            "            return False\n"
            "        self.inflight -= 1\n"
            "        return True\n"
        )
        assert codes(r) == ["JG027"]
        assert "self.inflight" in r.active[0].message

    def test_true_positive_inferred_pair(self):
        # no seeded name involved: open_stream/close_stream is inferred
        # from the class' dual method names sharing a self attribute
        r = run(
            "class StreamPool:\n"
            "    def __init__(self):\n"
            "        self._streams = []\n"
            "    def open_stream(self):\n"
            "        s = object()\n"
            "        self._streams.append(s)\n"
            "        return s\n"
            "    def close_stream(self, s):\n"
            "        self._streams.remove(s)\n"
            "def use():\n"
            "    pool = StreamPool()\n"
            "    s = pool.open_stream()\n"
            "    if s is None:\n"
            "        return None\n"
            "    pool.close_stream(s)\n"
            "    return s\n"
        )
        assert codes(r) == ["JG027"]
        assert "open_stream" in r.active[0].message

    def test_true_negative_try_finally(self):
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def snap(load, path):\n"
            "    LOCK.acquire()\n"
            "    try:\n"
            "        data = load(path)\n"
            "    finally:\n"
            "        LOCK.release()\n"
            "    return data\n"
        )
        assert codes(r) == []

    def test_true_negative_closed_on_every_branch(self):
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def both(flag):\n"
            "    LOCK.acquire()\n"
            "    if flag:\n"
            "        LOCK.release()\n"
            "    else:\n"
            "        LOCK.release()\n"
        )
        assert codes(r) == []

    def test_true_negative_ownership_returned(self):
        # the token leaves with the return value: the caller now owes the
        # refund, this frame is clean
        r = run(
            "def lease(BUDGET):\n"
            "    tok = BUDGET.take(1)\n"
            "    return tok\n"
            "def give_back(BUDGET, tok):\n"
            "    BUDGET.refund(tok)\n"
        )
        assert codes(r) == []

    def test_true_negative_start_stop_instance_idiom(self):
        # the close-half lives in a sibling method: the INSTANCE holds
        # the resource between start() and stop() — a transfer, not a leak
        r = run(
            "import threading\n"
            "class Pump:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def start(self):\n"
            "        self._lock.acquire()\n"
            "    def stop(self):\n"
            "        self._lock.release()\n"
        )
        assert codes(r) == []

    def test_true_negative_seeded_open_without_close_half_in_module(self):
        # atexit.register in a module that never unregisters is a
        # fire-and-forget API, not half of a protocol
        r = run(
            "import atexit\n"
            "def hook(fn):\n"
            "    atexit.register(fn)\n"
            "    return None\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG028 — unbalanced release
# ===========================================================================

class TestUnbalancedRelease:
    def test_true_positive_double_close(self):
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def bad():\n"
            "    LOCK.acquire()\n"
            "    LOCK.release()\n"
            "    LOCK.release()\n"
        )
        assert codes(r) == ["JG028"]
        assert "twice" in r.active[0].message

    def test_true_positive_double_close_via_branch(self):
        # one arm closes, the surviving path closes again
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def toggle(flag):\n"
            "    LOCK.acquire()\n"
            "    if flag:\n"
            "        LOCK.release()\n"
            "    LOCK.release()\n"
        )
        assert codes(r) == ["JG028"]

    def test_true_positive_close_without_open(self):
        # conditional open, unconditional close: the refund-without-take
        # shape that drives a ledger negative
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def maybe(flag):\n"
            "    if flag:\n"
            "        LOCK.acquire()\n"
            "    LOCK.release()\n"
        )
        assert codes(r) == ["JG028"]
        assert "never ran" in r.active[0].message

    def test_true_positive_loop_carried_release(self):
        # one open before the loop, the close inside the body: released
        # zero times or N times, never exactly once
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def pump(items):\n"
            "    LOCK.acquire()\n"
            "    for it in items:\n"
            "        LOCK.release()\n"
        )
        assert codes(r) == ["JG028"]
        assert "loop" in r.active[0].message

    def test_true_negative_close_then_reopen(self):
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def cycle():\n"
            "    LOCK.acquire()\n"
            "    LOCK.release()\n"
            "    LOCK.acquire()\n"
            "    LOCK.release()\n"
        )
        assert codes(r) == []

    def test_true_negative_branch_exit_then_close(self):
        # `close(); return` arm followed by a close on the surviving path
        # is exactly-once on both paths — not a double-close
        r = run(
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def guard(flag):\n"
            "    LOCK.acquire()\n"
            "    if flag:\n"
            "        LOCK.release()\n"
            "        return None\n"
            "    LOCK.release()\n"
            "    return True\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG029 — handoff without transfer
# ===========================================================================

class TestHandoffWithoutTransfer:
    def test_true_positive_thread_target_never_closes(self):
        # the pre-PR 6 device-capture bug: the lock is acquired, the
        # worker thread is handed ownership, and the worker never releases
        r = run(
            "import threading\n"
            "class Cap:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def grab(self):\n"
            "        self._lock.acquire()\n"
            "        threading.Thread(target=self._work, daemon=True).start()\n"
            "    def _work(self):\n"
            "        pass\n"
            "    def drop(self):\n"
            "        self._lock.release()\n"
        )
        assert codes(r) == ["JG029"]
        assert "self._work" in r.active[0].message

    def test_true_negative_receiver_closes_in_finally(self):
        # the PR 6 fix itself: the spawned worker releases in its finally
        # — the correct ownership-transfer idiom must not be punished
        r = run(
            "import threading\n"
            "class Cap:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def grab(self):\n"
            "        self._lock.acquire()\n"
            "        threading.Thread(target=self._work, daemon=True).start()\n"
            "    def _work(self):\n"
            "        try:\n"
            "            pass\n"
            "        finally:\n"
            "            self._lock.release()\n"
        )
        assert codes(r) == []

    def test_true_negative_unresolvable_target(self):
        # a target the project index cannot read stays a silent transfer:
        # the analyzer only indicts code it can actually see
        r = run(
            "import threading\n"
            "class Cap:\n"
            "    def __init__(self, fn):\n"
            "        self._lock = threading.Lock()\n"
            "        self._fn = fn\n"
            "    def grab(self):\n"
            "        self._lock.acquire()\n"
            "        threading.Thread(target=self._fn, daemon=True).start()\n"
            "    def drop(self):\n"
            "        self._lock.release()\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG030 — quantized-variant precision/cast mismatch
# ===========================================================================

class TestQuantPrecisionCastMismatch:
    def test_true_positive_declares_bf16_casts_fp16(self):
        # two incompatible 16-bit formats: the manifest promises bf16 (the
        # engine compiles a bfloat16 scope) but the bytes are fp16
        r = run(
            "import jax.numpy as jnp\n"
            "def build_variant(params, manifest):\n"
            "    casted = params.astype(jnp.float16)\n"
            "    manifest['precision'] = 'bf16'\n"
            "    return casted, manifest\n"
        )
        assert codes(r) == ["JG030"]
        assert "fp16" in r.active[0].message

    def test_true_positive_int8_kwarg_with_uint8_dtype(self):
        # declared through a precision= kwarg, contradicted by a dtype=
        # kwarg: uint8 weights under an int8 QuantDenseLayer contract
        r = run(
            "import numpy as np\n"
            "def publish(store, w):\n"
            "    q = np.asarray(w, dtype=np.uint8)\n"
            "    store.put(q, precision='int8')\n"
        )
        assert codes(r) == ["JG030"]

    def test_true_negative_matching_cast(self):
        # the correct builder: declared bf16, cast bf16 — extra f32
        # upcasts alongside (dequant outputs) never count against it
        r = run(
            "import jax.numpy as jnp\n"
            "def build_variant(params):\n"
            "    casted = params.astype(jnp.bfloat16)\n"
            "    scale = params.astype(jnp.float32)\n"
            "    return {'precision': 'bf16', 'p': casted, 's': scale}\n"
        )
        assert codes(r) == []

    def test_true_negative_declaration_without_casts(self):
        # byte-identical copy path (the int8 generator): a declaration
        # with no low-precision cast in scope is not evidence of anything
        r = run(
            "import shutil\n"
            "def copy_variant(src, dst, manifest):\n"
            "    shutil.copyfile(src, dst)\n"
            "    manifest['precision'] = 'int8'\n"
            "    return manifest\n"
        )
        assert codes(r) == []

    def test_true_negative_dispatch_table_both_precisions(self):
        # a scope naming BOTH precisions is a dispatch table, not a
        # single-variant builder — nothing to contradict
        r = run(
            "import jax.numpy as jnp\n"
            "def pick(kind, params):\n"
            "    table = {'precision': 'bf16'}\n"
            "    other = {'precision': 'int8'}\n"
            "    casted = params.astype(jnp.float16)\n"
            "    return table, other, casted\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG031 — hard-coded bucket ladder at a manifest-carrying load seam
# ===========================================================================

class TestHardcodedLadderLiteral:
    def test_true_positive_from_bundle_literal_list(self):
        # the bug the rule was derived from: a literal ladder at the
        # bundle seam silently overrides the learned manifest ladder
        r = run(
            "def load(path):\n"
            "    from serving.engine import ServingEngine\n"
            "    return ServingEngine.from_bundle(\n"
            "        path, buckets=[1, 8, 32, 128], replicas=2)\n"
        )
        assert codes(r) == ["JG031"]
        assert "manifest ladder" in r.active[0].message

    def test_true_positive_measure_bundle_cost_literal_tuple(self):
        # pricing a variant on a ladder it will never serve: the cost
        # block lands in the manifest next to the ladder it contradicts
        r = run(
            "from quant.cost import measure_bundle_cost\n"
            "def price(bundle_dir):\n"
            "    return measure_bundle_cost(bundle_dir, buckets=(1, 8))\n"
        )
        assert codes(r) == ["JG031"]

    def test_true_negative_buckets_none_and_absent(self):
        # the correct spellings: omit the kwarg or pass None — both let
        # the bundle's learned manifest ladder resolve
        r = run(
            "def load(path, engine_cls):\n"
            "    a = engine_cls.from_bundle(path)\n"
            "    b = engine_cls.from_bundle(path, buckets=None)\n"
            "    return a, b\n"
        )
        assert codes(r) == []

    def test_true_negative_computed_ladder(self):
        # a variable ladder is an operator/solver decision, not a guess:
        # args.buckets, DEFAULT_BUCKETS, or a solved ladder all pass
        r = run(
            "from serving.engine import DEFAULT_BUCKETS\n"
            "def load(path, engine_cls, args, learned):\n"
            "    a = engine_cls.from_bundle(path, buckets=args.buckets)\n"
            "    b = engine_cls.from_bundle(path, buckets=DEFAULT_BUCKETS)\n"
            "    c = engine_cls.from_bundle(path, buckets=learned or None)\n"
            "    return a, b, c\n"
        )
        assert codes(r) == []

    def test_true_negative_from_checkpoints_literal(self):
        # raw checkpoints carry no manifest — a literal ladder is the
        # only way to say anything at that seam
        r = run(
            "def load(gen, cv, engine_cls):\n"
            "    return engine_cls.from_checkpoints(\n"
            "        generator=gen, classifier=cv, buckets=(1, 8, 32))\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG032 — double buffer consumed while its overlapped fill is in flight
# ===========================================================================

class TestDoubleBufferMisuse:
    def test_true_positive_read_without_fence(self):
        # the classic double-buffering bug: the fill is issued against
        # `back`, then the consumer slices it with no fence — torn batches
        r = run(
            "def pump(pool, back, fill, n):\n"
            "    fut = pool.submit(fill, back, n)\n"
            "    first = back[0:n]\n"
            "    return first, fut\n"
        )
        assert codes(r) == ["JG032"]
        assert "fence" in r.active[0].message

    def test_true_positive_iteration_is_consumption(self):
        # for-iteration over the in-flight buffer is a read, same hazard
        r = run(
            "def drain(pool, buf):\n"
            "    pool.submit(self_refill, buf)\n"
            "    total = 0\n"
            "    for row in buf:\n"
            "        total += row\n"
            "    return total\n"
        )
        assert codes(r) == ["JG032"]

    def test_true_positive_thread_target_args(self):
        # Thread(target=..., args=(buf,)) is the same overlapped fill
        r = run(
            "import threading\n"
            "def pump(buf, prefetch_rows):\n"
            "    t = threading.Thread(target=prefetch_rows, args=(buf,))\n"
            "    t.start()\n"
            "    return buf[0]\n"
        )
        assert codes(r) == ["JG032"]

    def test_true_negative_fence_then_read(self):
        # zoo/streaming.py's discipline: result() fences the worker, the
        # read after it observes a fully-written buffer
        r = run(
            "def pump(pool, back, fill, n):\n"
            "    fut = pool.submit(fill, back, n)\n"
            "    fut.result()\n"
            "    return back[0:n]\n"
        )
        assert codes(r) == []

    def test_true_negative_swap_retires_buffer(self):
        # the tuple swap rebinds the names: post-swap reads refer to the
        # retired (fully written) storage, not the in-flight one
        r = run(
            "def pump(pool, front, back, fill):\n"
            "    pool.submit(fill, back)\n"
            "    front, back = back, front\n"
            "    return front[0], back[0]\n"
        )
        assert codes(r) == []

    def test_true_negative_read_before_issue(self):
        # consume-then-refill, the other legal ordering: the read
        # precedes the issue, so nothing in flight is observed
        r = run(
            "def pump(pool, buf, refill, n):\n"
            "    head = buf[0:n]\n"
            "    fut = pool.submit(refill, buf)\n"
            "    return head, fut\n"
        )
        assert codes(r) == []

    def test_true_negative_callee_without_fill_seam(self):
        # submit of a non-fill worker (a scorer, a logger) does not make
        # its arguments buffers — no naming seam, no hazard
        r = run(
            "def score(pool, rows, scorer):\n"
            "    fut = pool.submit(scorer, rows)\n"
            "    return rows[0], fut\n"
        )
        assert codes(r) == []

    def test_true_negative_thread_join_is_fence(self):
        # join() on the worker thread is as strong as result()
        r = run(
            "import threading\n"
            "def pump(buf, prefetch_rows):\n"
            "    t = threading.Thread(target=prefetch_rows, args=(buf,))\n"
            "    t.start()\n"
            "    t.join()\n"
            "    return buf[0]\n"
        )
        assert codes(r) == []


# ===========================================================================
# JG025 cross-class unification (satellite on the concurrency index)
# ===========================================================================

class TestCrossClassLockOrder:
    MANAGER = (
        "import threading\n"
        "from fx.worker import Worker\n"
        "class Manager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state_lock = threading.Lock()\n"
        "        self.worker = Worker(lock=self._lock,\n"
        "                             state_lock=self._state_lock)\n"
        "    def roll(self):\n"
        "        with self._state_lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )
    WORKER = (
        "class Worker:\n"
        "    def __init__(self, lock, state_lock):\n"
        "        self._lk = lock\n"
        "        self._st = state_lock\n"
        "    def tick(self):\n"
        "        with self._lk:\n"
        "            with self._st:\n"
        "                pass\n"
    )

    def test_true_positive_constructor_injected_inversion(self):
        # the documented JG025 false negative this satellite closes: the
        # manager nests state_lock->lock, the worker it constructed around
        # the SAME two locks nests lock->state_lock — neither module alone
        # contains a cycle
        report = analyze_sources({"fx/manager.py": self.MANAGER,
                                  "fx/worker.py": self.WORKER})
        assert [f.code for f in report.active] == ["JG025"]
        f = report.active[0]
        assert "Manager._lock" in f.message
        assert "Manager._state_lock" in f.message

    def test_true_positive_attribute_planted_inversion(self):
        # second sharing route: the locks are planted onto the worker by
        # attribute assignment after construction
        manager = (
            "import threading\n"
            "from fx.worker import Worker\n"
            "class Manager:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state_lock = threading.Lock()\n"
            "        self.worker = Worker()\n"
            "        self.worker._lk = self._lock\n"
            "        self.worker._st = self._state_lock\n"
            "    def roll(self):\n"
            "        with self._state_lock:\n"
            "            with self._lock:\n"
            "                pass\n"
        )
        worker = (
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lk = None\n"
            "        self._st = None\n"
            "    def tick(self):\n"
            "        with self._lk:\n"
            "            with self._st:\n"
            "                pass\n"
        )
        report = analyze_sources({"fx/manager.py": manager,
                                  "fx/worker.py": worker})
        assert [f.code for f in report.active] == ["JG025"]

    def test_finding_lands_once_in_the_closing_module(self):
        report = analyze_sources({"fx/manager.py": self.MANAGER,
                                  "fx/worker.py": self.WORKER})
        assert [f.path for f in report.active] == ["fx/manager.py"]

    def test_true_negative_consistent_order_across_classes(self):
        worker = self.WORKER.replace(
            "        with self._lk:\n"
            "            with self._st:\n",
            "        with self._st:\n"
            "            with self._lk:\n")
        report = analyze_sources({"fx/manager.py": self.MANAGER,
                                  "fx/worker.py": worker})
        assert [f.code for f in report.active] == []

    def test_true_negative_unshared_locks_do_not_unify(self):
        # same nesting shapes but the worker builds its OWN locks: no
        # injection route, no unification, no project-wide cycle
        manager = self.MANAGER.replace(
            "        self.worker = Worker(lock=self._lock,\n"
            "                             state_lock=self._state_lock)\n",
            "        self.worker = Worker()\n")
        worker = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lk = threading.Lock()\n"
            "        self._st = threading.Lock()\n"
            "    def tick(self):\n"
            "        with self._lk:\n"
            "            with self._st:\n"
            "                pass\n"
        )
        report = analyze_sources({"fx/manager.py": manager,
                                  "fx/worker.py": worker})
        assert [f.code for f in report.active] == []


# ===========================================================================
# Satellites: deterministic emission, --profile, gate staleness
# ===========================================================================

class TestDeterministicEmission:
    SOURCES = {
        "fx/b_mod.py": "def g(y):\n    assert y\n    return y\n",
        "fx/a_mod.py": (
            "def f(x):\n"
            "    assert x  # jaxlint: disable=JG003 (fixture)\n"
            "    assert x + 1\n"
            "    return x\n"
        ),
    }

    def _analyze(self, order):
        from gan_deeplearning4j_tpu.analysis import engine

        mods = [engine.parse_module(self.SOURCES[p], p) for p in order]
        baseline = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
                     "path": "fx/a_mod.py", "justification": "was fixed"}]
        return engine.analyze_modules(mods, baseline=baseline)

    def test_emission_is_byte_stable_across_module_order(self):
        # the same tree must render byte-identical text/JSON/SARIF no
        # matter how the walker enumerated files — diffs between two CI
        # runs must mean the findings changed, not the order did
        from gan_deeplearning4j_tpu.analysis import sarif

        r1 = self._analyze(["fx/a_mod.py", "fx/b_mod.py"])
        r2 = self._analyze(["fx/b_mod.py", "fx/a_mod.py"])
        assert r1.render_text() == r2.render_text()
        assert json.dumps(r1.to_json()) == json.dumps(r2.to_json())
        assert (json.dumps(sarif.to_sarif(r1, RULES, []))
                == json.dumps(sarif.to_sarif(r2, RULES, [])))

    def test_every_partition_is_sorted(self):
        r = self._analyze(["fx/b_mod.py", "fx/a_mod.py"])
        key = lambda f: (f.path, f.line, f.code)  # noqa: E731
        for part in (r.active, r.suppressed, r.baselined):
            assert [key(f) for f in part] == sorted(key(f) for f in part)
        assert r.warnings == sorted(r.warnings)

    def test_lifecycle_findings_are_order_stable(self):
        # the lifecycle rules (JG027-29) build a lazy project-wide index;
        # their findings must be byte-stable across enumeration order too
        srcs = {
            "fx/leak.py": (
                "import threading\n"
                "LOCK = threading.Lock()\n"
                "def f(x):\n"
                "    LOCK.acquire()\n"
                "    if x:\n"
                "        return None\n"
                "    LOCK.release()\n"
            ),
            "fx/clean.py": "def g(y):\n    return y\n",
        }
        r1 = analyze_sources(dict(srcs))
        r2 = analyze_sources(dict(reversed(list(srcs.items()))))
        assert [f.code for f in r1.active] == ["JG027"]
        assert r1.render_text() == r2.render_text()
        assert json.dumps(r1.to_json()) == json.dumps(r2.to_json())


class TestProfile:
    def test_report_carries_phase_and_rule_timings(self):
        r = run("def f(x):\n    assert x\n")
        prof = r.profile
        assert set(prof["phases"]) == {"parse", "index", "rules"}
        assert all(v >= 0 for v in prof["phases"].values())
        assert "JG003" in prof["rules"]

    def test_profile_is_not_part_of_the_emitted_report(self):
        # timings vary run to run; the byte-stable formats must not
        # include them
        r = run("def f(x):\n    assert x\n")
        assert "profile" not in r.to_json()
        assert "profile" not in r.render_text()

    def test_cli_profile_flag_prints_table_to_stderr(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
             str(p), "--no-baseline", "--profile"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "--profile (wall seconds)" in proc.stderr
        assert "phase parse" in proc.stderr
        assert "JG003" in proc.stderr
        # stdout is the unchanged report
        assert "JG003" in proc.stdout and "--profile" not in proc.stdout


class TestSuppressionInterplay:
    def test_unknown_code_in_mixed_disable_still_warns(self):
        # satellite: disabling a real rule next to a typo'd one must keep
        # the typo warning — otherwise the typo silently suppresses nothing
        # and nobody ever learns
        src = (
            "import threading\n"
            "class Sampler:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.counts = {}\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop, daemon=True).start()\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self.counts['a'] = self.counts.get('a', 0) + 1\n"
            "        with self._lock:\n"
            "            self.counts['b'] = 1\n"
            "    def snapshot(self):\n"
            "        return dict(self.counts)  # jaxlint: disable=JG024,JG99X\n"
        )
        r = run(src)
        assert codes(r) == []
        assert len(r.suppressed) == 1
        assert any("JG99X" in w for w in r.warnings)


class TestLintGateScript:
    def _gate(self, *args, env=None):
        import shutil

        if shutil.which("bash") is None:  # pragma: no cover
            pytest.skip("no bash in container")
        return subprocess.run(
            ["bash", "scripts/lint_gate.sh", *args],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, **(env or {})},
        )

    def test_full_gate_fails_on_stale_baseline(self, tmp_path):
        # satellite: --full is the campaign preflight and the tier-1 shape;
        # a baseline entry whose bug was fixed must FAIL it, not linger
        bl = tmp_path / "stale.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
             "path": "bench.py", "justification": "fixed long ago"}
        ]}))
        proc = self._gate("--full", "--rules", "JG003",
                          "--baseline", str(bl))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "stale baseline entry" in proc.stdout

    def test_profile_env_passthrough(self):
        proc = self._gate("--full", "--rules", "JG003",
                          env={"LINT_PROFILE": "1"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "--profile (wall seconds)" in proc.stderr

    def test_gate_wires_the_parse_cache(self, tmp_path):
        # lint_gate.sh exports JAXLINT_CACHE_DIR so every shape shares one
        # cache; the profile table proves the analyzer picked it up
        proc = self._gate("--full", "--rules", "JG003",
                          env={"LINT_PROFILE": "1",
                               "JAXLINT_CACHE_DIR": str(tmp_path)})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "cache hits" in proc.stderr

    def test_lint_cache_off_bypasses_the_gate_cache(self, tmp_path):
        proc = self._gate("--full", "--rules", "JG003",
                          env={"LINT_PROFILE": "1", "LINT_CACHE": "off",
                               "JAXLINT_CACHE_DIR": str(tmp_path)})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "cache hits" not in proc.stderr


# ===========================================================================
# Satellites: parse cache, fingerprint v2 + migration, --changed-only scoping
# ===========================================================================

class TestParseCache:
    LEAKY = (
        "import threading\n"
        "LOCK = threading.Lock()\n"
        "def f(x):\n"
        "    LOCK.acquire()\n"
        "    if x:\n"
        "        return None\n"
        "    LOCK.release()\n"
    )
    CLEAN = "def g(y):\n    return y\n"

    def _tree(self, tmp_path):
        (tmp_path / "leaky.py").write_text(self.LEAKY)
        (tmp_path / "clean.py").write_text(self.CLEAN)

    def _run(self, tmp_path, cache):
        return analyze_paths(["leaky.py", "clean.py"], root=str(tmp_path),
                             cache=cache)

    def test_warm_run_equals_cold_run_finding_for_finding(self, tmp_path):
        from gan_deeplearning4j_tpu.analysis import engine

        self._tree(tmp_path)
        cold_cache = engine.ParseCache(str(tmp_path / "cache"))
        cold = self._run(tmp_path, cold_cache)
        assert cold_cache.stats == {"hits": 0, "misses": 2}
        warm_cache = engine.ParseCache(str(tmp_path / "cache"))
        warm = self._run(tmp_path, warm_cache)
        assert warm_cache.stats == {"hits": 2, "misses": 0}
        assert [f.code for f in cold.active] == ["JG027"]
        assert cold.render_text() == warm.render_text()
        assert json.dumps(cold.to_json()) == json.dumps(warm.to_json())

    def test_edit_invalidates_exactly_that_file(self, tmp_path):
        from gan_deeplearning4j_tpu.analysis import engine

        self._tree(tmp_path)
        self._run(tmp_path, engine.ParseCache(str(tmp_path / "cache")))
        (tmp_path / "leaky.py").write_text(self.LEAKY + "# touched\n")
        cache = engine.ParseCache(str(tmp_path / "cache"))
        r = self._run(tmp_path, cache)
        assert cache.stats == {"hits": 1, "misses": 1}
        assert [f.code for f in r.active] == ["JG027"]

    def test_corrupt_entry_degrades_to_miss(self, tmp_path):
        from gan_deeplearning4j_tpu.analysis import engine

        self._tree(tmp_path)
        cold = self._run(tmp_path, engine.ParseCache(str(tmp_path / "cache")))
        for blob in (tmp_path / "cache").iterdir():
            blob.write_bytes(b"not a pickle")
        cache = engine.ParseCache(str(tmp_path / "cache"))
        r = self._run(tmp_path, cache)
        assert cache.stats == {"hits": 0, "misses": 2}
        assert r.render_text() == cold.render_text()

    def test_cli_cache_dir_profile_and_identical_output(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        args = [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
                str(p), "--no-baseline", "--rules", "JG003", "--profile",
                "--cache-dir", str(tmp_path / "cache")]
        p1 = subprocess.run(args, capture_output=True, text=True, cwd=REPO)
        p2 = subprocess.run(args, capture_output=True, text=True, cwd=REPO)
        assert p1.returncode == p2.returncode == 1
        assert "cache hits 0 / misses 1" in p1.stderr
        assert "cache hits 1 / misses 0" in p2.stderr
        assert p1.stdout == p2.stdout

    def test_cli_lint_cache_off_escape_hatch(self, tmp_path):
        p = tmp_path / "dirty.py"
        p.write_text("def f(x):\n    assert x\n    return x\n")
        proc = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
             str(p), "--no-baseline", "--rules", "JG003", "--profile",
             "--cache-dir", str(tmp_path / "cache")],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "LINT_CACHE": "off"},
        )
        assert proc.returncode == 1
        assert "cache hits" not in proc.stderr


class TestChangedOnlyScoping:
    def test_check_paths_restricts_the_rule_phase(self, tmp_path):
        (tmp_path / "a.py").write_text("def f(x):\n    assert x\n")
        (tmp_path / "b.py").write_text("def g(y):\n    assert y\n")
        r = analyze_paths(["a.py", "b.py"], root=str(tmp_path),
                          check_paths={"a.py"})
        assert [f.path for f in r.active] == ["a.py"]

    def test_unchecked_files_still_feed_the_index(self, tmp_path):
        # the point of parsing the full target set under --changed-only:
        # a cross-module rule checking only rules.py must still see the
        # metric family registered in (unchanged) metrics.py
        (tmp_path / "metrics.py").write_text(
            "from gan_deeplearning4j_tpu.telemetry.registry import get_registry\n"
            "def families():\n"
            "    get_registry().gauge('fleet_pressure_real', 'x')\n"
        )
        (tmp_path / "rules.py").write_text(
            "from gan_deeplearning4j_tpu.telemetry.alerts import AlertRule\n"
            "def rules():\n"
            "    return [AlertRule(name='p', kind='anomaly',\n"
            "                      metric='fleet_pressure_reel')]\n"
        )
        r = analyze_paths(["metrics.py", "rules.py"], root=str(tmp_path),
                          check_paths={"rules.py"})
        assert [f.code for f in r.active] == ["JG023"]
        assert "fleet_pressure_reel" in r.active[0].message

    def test_baseline_staleness_is_scoped_to_checked_files(self, tmp_path):
        # an entry for an UNCHECKED file must not read as stale just
        # because the rule phase skipped that file this run
        (tmp_path / "a.py").write_text("def f(x):\n    assert x\n")
        (tmp_path / "b.py").write_text("def g(y):\n    return y\n")
        baseline = [{"fingerprint": "deadbeefdeadbeef", "rule": "JG003",
                     "path": "a.py", "justification": "someone else's"}]
        r = analyze_paths(["a.py", "b.py"], root=str(tmp_path),
                          baseline=baseline, check_paths={"b.py"})
        assert r.active == [] and r.stale_baseline == []
        full = analyze_paths(["a.py", "b.py"], root=str(tmp_path),
                             baseline=baseline)
        assert full.stale_baseline != []  # the full run still catches it


class TestFingerprintV2:
    def test_context_disambiguates_identical_snippets(self):
        # two byte-identical offending lines in one file: the legacy
        # scheme collides, the neighbor-context scheme does not
        r = run("def f(x):\n    assert x\n    y = 1\n    assert x\n")
        assert [f.code for f in r.active] == ["JG003", "JG003"]
        a, b = r.active
        assert a.legacy_fingerprint == b.legacy_fingerprint
        assert a.fingerprint != b.fingerprint

    def test_spacing_only_edit_keeps_the_fingerprint(self):
        a = run("def f(x):\n    assert x\n    return x\n").active[0]
        b = run("def f(x):\n\n    assert x\n\n    return x\n").active[0]
        assert a.fingerprint == b.fingerprint

    def test_neighbor_edit_stales_the_fingerprint(self):
        a = run("def f(x):\n    assert x\n    return x\n").active[0]
        b = run("def f(x):\n    assert x\n    return x + 1\n").active[0]
        assert a.fingerprint != b.fingerprint

    def test_legacy_entry_matches_and_records_the_migration(self):
        src = "def f(x):\n    assert x\n    return x\n"
        probe = analyze_source(src, path="fx/mod.py").active[0]
        baseline = [{"fingerprint": probe.legacy_fingerprint,
                     "rule": "JG003", "path": "fx/mod.py",
                     "justification": "pre-migration entry"}]
        r = analyze_source(src, path="fx/mod.py", baseline=baseline)
        assert r.active == []
        assert [f.code for f in r.baselined] == ["JG003"]
        assert r.stale_baseline == []
        assert r.baseline_migrations == {
            probe.legacy_fingerprint: probe.fingerprint}

    def test_current_entry_records_no_migration(self):
        src = "def f(x):\n    assert x\n    return x\n"
        probe = analyze_source(src, path="fx/mod.py").active[0]
        baseline = [{"fingerprint": probe.fingerprint, "rule": "JG003",
                     "path": "fx/mod.py", "justification": "current"}]
        r = analyze_source(src, path="fx/mod.py", baseline=baseline)
        assert r.active == [] and r.baseline_migrations == {}

    def test_cli_auto_migrates_the_baseline_file(self, tmp_path):
        src = "def f(x):\n    assert x\n    return x\n"
        (tmp_path / "dirty.py").write_text(src)
        probe = analyze_source(src, path="dirty.py").active[0]
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": probe.legacy_fingerprint, "rule": "JG003",
             "path": "dirty.py", "justification": "pre-migration entry"}
        ]}))
        args = [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
                "dirty.py", "--rules", "JG003", "--baseline", str(bl)]
        env = {**os.environ, "PYTHONPATH": REPO}
        p1 = subprocess.run(args, capture_output=True, text=True,
                            cwd=str(tmp_path), env=env)
        assert p1.returncode == 0, p1.stdout + p1.stderr
        assert "migrated 1 baseline entry" in p1.stderr
        entries = json.loads(bl.read_text())["entries"]
        assert entries[0]["fingerprint"] == probe.fingerprint
        # second run matches directly: no further rewrite
        p2 = subprocess.run(args, capture_output=True, text=True,
                            cwd=str(tmp_path), env=env)
        assert p2.returncode == 0 and "migrated" not in p2.stderr

    def test_cli_lifecycle_stats_artifact(self, tmp_path):
        (tmp_path / "leaky.py").write_text(TestParseCache.LEAKY)
        out = tmp_path / "stats.json"
        proc = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.analysis",
             "leaky.py", "--no-baseline", "--lifecycle-stats", str(out)],
            capture_output=True, text=True, cwd=str(tmp_path),
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1  # the leak is an active finding
        stats = json.loads(out.read_text())
        assert stats["opens"] >= 1 and stats["leaked"] >= 1
        assert stats["pairs_seeded"] >= 5
