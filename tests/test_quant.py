"""quant/ — real quantized variants with measured cost (docs/QUANT.md).

What must hold:

- **calibration is deterministic**: same probe rows, same float params →
  bit-identical activation scales, across independent builds;
- **outputs stay close**: a bf16 or int8 variant serves within tight
  tolerance of its fp32 source on every request kind (and the int8
  generator is byte-identical — PTQ is the classifier's trade);
- **a quantized bundle is just a bundle**: serializer round-trip
  preserves int8 params exactly, the engine serves it through
  ``from_bundle``, and ``QuantDenseLayer`` resolves lazily in a process
  that never imported quant/;
- **the canary gate polices quantization loss**: a sane int8 variant is
  admitted, an over-degraded one (garbage calibration) is rejected
  through the same relative thresholds every reload candidate faces;
- **the mux economics run on the measurement**: manifest cost blocks
  are adopted at ``add()``, ``set_measured_cost`` flips declared →
  measured live, and residency eviction picks its victim by the
  measured scalar even when the declared bootstrap says otherwise.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from gan_deeplearning4j_tpu.deploy.canary import CanaryGate  # noqa: E402
from gan_deeplearning4j_tpu.nn import (  # noqa: E402
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.nn.layers import layer_from_dict  # noqa: E402
from gan_deeplearning4j_tpu.quant import (  # noqa: E402
    QuantDenseLayer,
    build_bf16_variant,
    build_int8_variant,
    calibrate_activation_scales,
    cast_params_bf16,
    default_calibration_rows,
    manifest_cost,
    measure_engine_cost,
    quantize_classifier,
    quantize_dense_params,
    write_cost_block,
)
from gan_deeplearning4j_tpu.serving import ServingEngine  # noqa: E402
from gan_deeplearning4j_tpu.serving.mux import MuxRegistry  # noqa: E402
from gan_deeplearning4j_tpu.utils import write_model  # noqa: E402
from gan_deeplearning4j_tpu.utils.serializer import read_model  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES, HIDDEN = 4, 6, 3, 5


def tiny_generator(seed=1):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8, activation="tanh"), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    return b.build()


def tiny_classifier(seed=2):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=HIDDEN, activation="tanh"), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    return b.build()


def confident_cv_params(cv):
    """Classifier params with well-separated logits (weights scaled up),
    so int8 rounding cannot flip argmax decisions on the probe rows —
    the 'trained' incumbent the canary accuracy probe needs."""
    params = cv.init()
    rng = np.random.default_rng(7)

    def _scale(leaf):
        a = np.asarray(leaf)
        if a.ndim == 2:  # weights: re-draw wide
            return jnp.asarray(
                rng.standard_normal(a.shape).astype(np.float32) * 2.0)
        return jnp.asarray(a)

    return jax.tree_util.tree_map(_scale, params)


def write_fp32_bundle(directory, *, confident=False, generation=0):
    os.makedirs(directory, exist_ok=True)
    gen, cv = tiny_generator(), tiny_classifier()
    cv_params = confident_cv_params(cv) if confident else cv.init()
    write_model(os.path.join(directory, "gen.zip"), gen, gen.init(),
                save_updater=False)
    write_model(os.path.join(directory, "cv.zip"), cv, cv_params,
                save_updater=False)
    manifest = {
        "format_version": 1,
        "generator": "gen.zip",
        "classifier": "cv.zip",
        "feature_vertex": "feat_1",
        "generation": generation,
        "step": 0,
    }
    with open(os.path.join(directory, "serving.json"), "w") as fh:
        json.dump(manifest, fh)
    return manifest


def engine(directory, **kw):
    kw.setdefault("buckets", (1, 8))
    kw.setdefault("export_gauge", False)
    e = ServingEngine.from_bundle(directory, **kw)
    e.warmup()
    return e


# ===========================================================================
# calibration determinism
# ===========================================================================

class TestCalibrationDeterminism:
    def test_scales_bit_identical_across_independent_builds(self, tmp_path):
        src = str(tmp_path / "src")
        write_fp32_bundle(src)
        m1 = build_int8_variant(src, str(tmp_path / "a"))
        m2 = build_int8_variant(src, str(tmp_path / "b"))
        s1 = m1["quant"]["calibration"]["activation_scales"]
        s2 = m2["quant"]["calibration"]["activation_scales"]
        # bit-identical floats, not approximately equal
        assert s1 == s2
        assert set(s1) == {"feat_1", "cv_out"}
        assert all(v > 0 for v in s1.values())

    def test_calibrate_twice_from_fresh_loads(self, tmp_path):
        src = str(tmp_path / "src")
        write_fp32_bundle(src)
        rows = default_calibration_rows(FEAT, num_rows=32)
        scales = []
        for _ in range(2):
            graph, params, _, _ = read_model(
                os.path.join(src, "cv.zip"), load_updater=False)
            scales.append(calibrate_activation_scales(graph, params, rows))
        assert scales[0] == scales[1]

    def test_fallback_rows_are_seeded_and_stable(self):
        a = default_calibration_rows(FEAT, num_rows=16, seed=5)
        b = default_calibration_rows(FEAT, num_rows=16, seed=5)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.float32 and a.shape == (16, FEAT)

    def test_manifest_provenance_names_row_source(self, tmp_path):
        src = str(tmp_path / "src")
        write_fp32_bundle(src)
        fallback = build_int8_variant(src, str(tmp_path / "f"))
        caller = build_int8_variant(
            src, str(tmp_path / "c"),
            calibration_rows=np.ones((8, FEAT), np.float32))
        assert (fallback["quant"]["calibration"]["source"]
                == "seeded_fallback")
        assert (caller["quant"]["calibration"]["source"]
                == "caller_probe_batch")
        assert caller["quant"]["calibration"]["num_rows"] == 8


# ===========================================================================
# output tolerance vs fp32
# ===========================================================================

class TestOutputTolerance:
    def test_bf16_variant_outputs_close_on_every_kind(self, tmp_path):
        src, var = str(tmp_path / "src"), str(tmp_path / "bf16")
        write_fp32_bundle(src)
        m = build_bf16_variant(src, var)
        assert m["precision"] == "bf16"
        e_fp, e_bf = engine(src), engine(var)
        assert e_bf.stats()["precision"] == "bf16"
        for kind in e_fp.kinds:
            rows = np.random.default_rng(3).random(
                (5, e_fp.input_width(kind))).astype(np.float32)
            a = np.asarray(e_fp.run(kind, rows), np.float32)
            b = np.asarray(e_bf.run(kind, rows), np.float32)
            # bf16 has ~3 decimal digits; outputs here are O(1)
            np.testing.assert_allclose(a, b, atol=0.05), kind

    def test_int8_classifier_close_and_generator_byte_identical(
            self, tmp_path):
        src, var = str(tmp_path / "src"), str(tmp_path / "int8")
        write_fp32_bundle(src, confident=True)
        m = build_int8_variant(src, var)
        assert m["precision"] == "int8"
        with open(os.path.join(src, "gen.zip"), "rb") as fh:
            src_gen = fh.read()
        with open(os.path.join(var, "gen.zip"), "rb") as fh:
            var_gen = fh.read()
        assert src_gen == var_gen
        e_fp, e_q = engine(src), engine(var)
        rows = np.random.default_rng(4).random((6, FEAT)).astype(np.float32)
        a = np.asarray(e_fp.run("classify", rows), np.float32)
        b = np.asarray(e_q.run("classify", rows), np.float32)
        # per-channel symmetric PTQ on a 2-dense classifier: probability
        # error stays well inside the canary's accuracy tolerance
        np.testing.assert_allclose(a, b, atol=0.08)
        assert (np.argmax(a, axis=1) == np.argmax(b, axis=1)).all()

    def test_quant_dense_params_reconstruct_weights(self):
        w = np.random.default_rng(5).standard_normal(
            (FEAT, CLASSES)).astype(np.float32)
        b = np.zeros((CLASSES,), np.float32)
        q = quantize_dense_params(w, b, act_scale=0.01)
        assert np.asarray(q["W_q"]).dtype == np.int8
        recon = np.asarray(q["W_q"], np.float32) * np.asarray(q["w_scale"])
        # per-output-channel scale: worst-case error is half a quantum
        quantum = np.asarray(q["w_scale"])[None, :]
        assert (np.abs(recon - w) <= quantum * 0.5 + 1e-7).all()


# ===========================================================================
# quantized-bundle round-trip
# ===========================================================================

class TestQuantBundleRoundTrip:
    def test_int8_params_survive_serializer_exactly(self, tmp_path):
        cv = tiny_classifier()
        rows = default_calibration_rows(FEAT, num_rows=16)
        qgraph, qparams, _ = quantize_classifier(cv, cv.init(), rows)
        path = str(tmp_path / "q.zip")
        write_model(path, qgraph, qparams, save_updater=False)
        graph2, params2, _, _ = read_model(path, load_updater=False)
        for name in ("feat_1", "cv_out"):
            v = next(v for v in graph2.vertices if v.name == name)
            assert isinstance(v.layer, QuantDenseLayer)
            np.testing.assert_array_equal(
                np.asarray(qparams[name]["W_q"]),
                np.asarray(params2[name]["W_q"]))
            assert np.asarray(params2[name]["W_q"]).dtype == np.int8
            np.testing.assert_array_equal(
                np.asarray(qparams[name]["w_scale"]),
                np.asarray(params2[name]["w_scale"]))

    def test_act_scale_survives_graph_dict_round_trip(self):
        cv = tiny_classifier()
        rows = default_calibration_rows(FEAT, num_rows=16)
        qgraph, _, scales = quantize_classifier(cv, cv.init(), rows)
        rebuilt = type(qgraph).from_dict(qgraph.to_dict())
        for v in rebuilt.vertices:
            if isinstance(v.layer, QuantDenseLayer):
                assert v.layer.act_scale == scales[v.name]

    def test_quantized_bundle_serves_through_from_bundle(self, tmp_path):
        src, var = str(tmp_path / "src"), str(tmp_path / "int8")
        write_fp32_bundle(src)
        build_int8_variant(src, var)
        e = engine(var)
        assert set(e.kinds) == {"sample", "classify", "features"}
        out = np.asarray(e.run("classify",
                               np.ones((3, FEAT), np.float32)))
        assert out.shape == (3, CLASSES)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-3)

    def test_layer_resolves_lazily_without_importing_quant(self, tmp_path):
        # a reload process that never imported quant/ must still load a
        # quantized bundle: layer_from_dict imports the owning module on
        # first sight of the type name
        src, var = str(tmp_path / "src"), str(tmp_path / "int8")
        write_fp32_bundle(src)
        build_int8_variant(src, var)
        code = (
            "import sys\n"
            "from gan_deeplearning4j_tpu.utils.serializer import read_model\n"
            "assert not any(m.startswith('gan_deeplearning4j_tpu.quant')\n"
            "               for m in sys.modules), 'quant imported eagerly'\n"
            f"g, p, _, _ = read_model({os.path.join(var, 'cv.zip')!r},\n"
            "                        load_updater=False)\n"
            "kinds = {type(v.layer).__name__ for v in g.vertices if v.layer}\n"
            "assert 'QuantDenseLayer' in kinds, kinds\n"
            "print('lazy-ok')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        assert "lazy-ok" in proc.stdout

    def test_bf16_variant_int8_refused_without_classifier(self, tmp_path):
        # generator-only bundle: bf16 builds, int8 refuses loudly
        src = str(tmp_path / "src")
        os.makedirs(src)
        gen = tiny_generator()
        write_model(os.path.join(src, "gen.zip"), gen, gen.init(),
                    save_updater=False)
        with open(os.path.join(src, "serving.json"), "w") as fh:
            json.dump({"format_version": 1, "generator": "gen.zip",
                       "generation": 0, "step": 0}, fh)
        m = build_bf16_variant(src, str(tmp_path / "bf16"))
        assert m["precision"] == "bf16"
        with pytest.raises(ValueError, match="no classifier"):
            build_int8_variant(src, str(tmp_path / "int8"))


# ===========================================================================
# canary gating of quantization loss
# ===========================================================================

class TestCanaryGatesQuantization:
    def _gate_fixture(self, tmp_path):
        src = str(tmp_path / "src")
        write_fp32_bundle(src, confident=True)
        e_fp = engine(src)
        rows = np.random.default_rng(11).random(
            (48, FEAT)).astype(np.float32)
        # labels from the fp32 incumbent itself: incumbent accuracy is
        # 1.0 by construction, so a real degradation is visible through
        # the relative accuracy floor
        labels = np.argmax(np.asarray(e_fp.run("classify", rows)), axis=1)
        gate = CanaryGate(rows, labels, num_samples=16, seed=1)
        return src, e_fp, rows, gate

    def test_sane_int8_variant_admitted(self, tmp_path):
        src, e_fp, rows, gate = self._gate_fixture(tmp_path)
        var = str(tmp_path / "int8")
        build_int8_variant(src, var, calibration_rows=rows)
        decision = gate.evaluate(engine(var), e_fp)
        assert decision.passed, decision.reason
        assert decision.candidate["accuracy"] is not None

    def test_over_degraded_int8_rejected(self, tmp_path):
        src, e_fp, rows, gate = self._gate_fixture(tmp_path)
        var = str(tmp_path / "degraded")
        # garbage calibration: probe rows a billion times out of range
        # drive the activation scales so high every input quantizes to
        # zero — classify collapses to a constant prediction
        build_int8_variant(src, var, calibration_rows=rows * 1e9)
        decision = gate.evaluate(engine(var), e_fp)
        assert not decision.passed
        assert "accuracy" in decision.reason


# ===========================================================================
# measured cost + mux economics
# ===========================================================================

class _FakeEngine:
    def __init__(self, name, generation=None):
        self.name = name
        self.generation = generation
        self.warmed = True
        self.kinds = ("sample",)

    def warmup(self, background=False):
        return {}

    def input_width(self, kind):
        return Z

    def dispatch(self, kind, rows_list):
        return types.SimpleNamespace(
            lane=0, rows=[np.asarray(r) for r in rows_list])

    def finalize(self, flight):
        return np.concatenate(flight.rows)


def fake_registry(budget=2):
    return MuxRegistry(
        buckets=(1, 8), budget=budget,
        build=lambda v: _FakeEngine(v.name, generation=v.generation),
        batcher_kwargs={"max_latency": 0.0, "default_timeout": 2.0})


def cost_block(scalar, resident=1000):
    return {"cost_schema": 1, "scalar": scalar, "per_row_s": 1e-6,
            "resident_param_bytes": resident, "precision": "fp32"}


class TestMeasuredCostEconomics:
    def test_measured_engine_cost_prices_bf16_below_fp32(self, tmp_path):
        src, var = str(tmp_path / "src"), str(tmp_path / "bf16")
        write_fp32_bundle(src)
        build_bf16_variant(src, var)
        b_fp = measure_engine_cost(engine(src), rounds=1)
        b_bf = measure_engine_cost(engine(var), rounds=1)
        assert b_bf["resident_param_bytes"] * 2 == b_fp[
            "resident_param_bytes"]
        assert b_bf["precision"] == "bf16"
        assert set(b_fp["per_bucket_s"]) == {"sample", "classify",
                                             "features"}
        assert b_fp["scalar"] > 0

    def test_cost_block_manifest_round_trip(self, tmp_path):
        d = str(tmp_path / "b")
        write_fp32_bundle(d)
        assert manifest_cost(d) is None  # bootstrap: no block yet
        write_cost_block(d, cost_block(3.5))
        block = manifest_cost(d)
        assert block is not None and block["scalar"] == 3.5
        # a garbage block is a bootstrap case, not an adoption
        write_cost_block(d, {"scalar": -1})
        assert manifest_cost(d) is None

    def test_add_adopts_manifest_cost_block(self, tmp_path):
        d = str(tmp_path / "b")
        write_fp32_bundle(d)
        write_cost_block(d, cost_block(0.25, resident=512))
        reg = fake_registry()
        v = reg.add("m", bundle_path=d, cost=4.0, weight=0.0)
        assert v.cost == 0.25 and v.cost_source == "measured"
        assert v.declared_cost == 4.0
        snap = reg.snapshot()["variants"]["m"]
        assert snap["cost_source"] == "measured"
        assert snap["declared_cost"] == 4.0
        assert snap["resident_param_bytes"] == 512

    def test_set_measured_cost_flips_declared_to_measured(self):
        reg = fake_registry()
        reg.add("m", bundle_path="/nowhere", cost=4.0, weight=0.0)
        assert reg.cost_sources() == {"m": "declared"}
        reg.set_measured_cost("m", cost_block(0.5))
        assert reg.cost_sources() == {"m": "measured"}
        assert reg.costs() == {"m": 0.5}
        assert any(e["event"] == "cost_measured" for e in reg.events)
        with pytest.raises(ValueError, match="positive"):
            reg.set_measured_cost("m", {"scalar": 0})

    def test_eviction_victim_follows_measured_not_declared(self):
        # declared says "a" is the expensive one; the measurement says
        # "b" is. At equal weight the budget must demote "b" first.
        reg = fake_registry(budget=2)
        reg.add("a", bundle_path="/a", cost=9.0, weight=0.4)
        reg.add("b", bundle_path="/b", cost=1.0, weight=0.4)
        reg.set_measured_cost("a", cost_block(0.1))
        reg.set_measured_cost("b", cost_block(7.0))
        reg.add("c", bundle_path="/c", cost=1.0, weight=0.4)
        assert sorted(reg.resident_names()) == ["a", "c"]
        assert reg.variant("b").state == "cold"
