"""serving/mux subsystem tests: weighted-splitter determinism and
minimal-reassignment, the shared staging pool, registry residency budget +
eviction + re-warm, the continuous canary ramp with auto-rollback,
per-model brownout tiering, the multi-model service end-to-end over real
(tiny) engines, the registry-mode reload plane, and the fleet merge's
model/generation label pass-through (docs/MULTIPLEX.md).

Engine tests reuse the tiny dense graphs the serving suite uses —
millisecond compiles, identical physics to the MNIST stack."""

import json
import os
import threading
import types

import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.serving import ServingEngine, make_server
from gan_deeplearning4j_tpu.serving.mux import (
    BrownoutController,
    MuxRegistry,
    MuxService,
    RampController,
    SharedStagingPool,
    WeightedSplitter,
    health_from_tracker,
)
from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig, SLOTracker
from gan_deeplearning4j_tpu.utils import write_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES, HIDDEN = 4, 6, 3, 5


def tiny_generator(seed=1):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    return b.build()


def tiny_classifier(seed=2):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=HIDDEN), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    return b.build()


def write_bundle(directory, *, gen_seed=1, generation=None):
    """A serving bundle (gen zip + serving.json) in ``directory``."""
    os.makedirs(directory, exist_ok=True)
    gen = tiny_generator(seed=gen_seed)
    write_model(os.path.join(directory, "gen.zip"), gen, gen.init(),
                save_updater=False)
    manifest = {
        "format_version": 1,
        "generator": "gen.zip",
        "generation": generation,
    }
    with open(os.path.join(directory, "serving.json"), "w") as fh:
        json.dump(manifest, fh)
    return manifest


# ===========================================================================
# weighted splitter — the determinism satellite
# ===========================================================================

KEYS = [f"user-{i}" for i in range(4000)]


class TestWeightedSplitter:
    def test_same_key_same_variant_across_restarts(self):
        # the satellite invariant: assignment is a pure function of
        # (key, weights) — a fresh splitter (a restarted router) agrees
        # on every key at fixed weights
        a = WeightedSplitter({"inc": 0.9, "can": 0.1})
        b = WeightedSplitter({"inc": 0.9, "can": 0.1})
        assert [a.assign(k) for k in KEYS] == [b.assign(k) for k in KEYS]

    def test_split_is_weight_proportional(self):
        s = WeightedSplitter({"inc": 0.9, "can": 0.1})
        got = sum(1 for k in KEYS if s.assign(k) == "can") / len(KEYS)
        # binomial n=4000 p=0.1: 5 sigma ~ 0.024
        assert abs(got - 0.1) < 0.03, got

    def test_weight_change_moves_only_the_expected_fraction(self):
        # the satellite invariant: raising one variant's weight moves
        # keys ONLY toward it, and in ~the share-delta proportion —
        # a ramp step disturbs precisely the traffic it admits
        s = WeightedSplitter({"inc": 0.9, "can": 0.1})
        before = {k: s.assign(k) for k in KEYS}
        s.set_weight("can", 0.9)  # share 0.10 -> 0.50
        after = {k: s.assign(k) for k in KEYS}
        moved = [k for k in KEYS if before[k] != after[k]]
        assert all(after[k] == "can" for k in moved)
        frac = len(moved) / len(KEYS)
        assert abs(frac - 0.4) < 0.04, frac
        # and lowering it back restores the original assignment exactly
        s.set_weight("can", 0.1)
        assert {k: s.assign(k) for k in KEYS} == before

    def test_three_way_split_and_zero_weight_exclusion(self):
        s = WeightedSplitter({"a": 0.5, "b": 0.3, "c": 0.2})
        counts = {"a": 0, "b": 0, "c": 0}
        for k in KEYS:
            counts[s.assign(k)] += 1
        for name, share in (("a", 0.5), ("b", 0.3), ("c", 0.2)):
            assert abs(counts[name] / len(KEYS) - share) < 0.04
        s.set_weight("b", 0.0)
        assert all(s.assign(k) != "b" for k in KEYS[:500])

    def test_among_restricts_candidates(self):
        s = WeightedSplitter({"a": 0.5, "b": 0.5})
        assert all(s.assign(k, among=["a"]) == "a" for k in KEYS[:100])

    def test_no_positive_weight_raises(self):
        s = WeightedSplitter({"a": 0.0})
        with pytest.raises(LookupError):
            s.assign("k")

    def test_weight_validation(self):
        s = WeightedSplitter()
        with pytest.raises(ValueError):
            s.set_weight("a", -0.1)
        with pytest.raises(ValueError):
            s.set_weight("a", float("nan"))

    def test_shares_normalize(self):
        s = WeightedSplitter({"a": 3.0, "b": 1.0})
        assert s.shares() == {"a": 0.75, "b": 0.25}


# ===========================================================================
# shared staging pool
# ===========================================================================

class TestSharedStagingPool:
    def test_checkin_checkout_reuses_buffers(self):
        pool = SharedStagingPool()
        buf = pool.checkout(8, FEAT)
        pool.checkin(buf)
        assert pool.checkout(8, FEAT) is buf
        assert pool.stats()["allocated_total"] == 1

    def test_pool_is_bounded_per_key(self):
        pool = SharedStagingPool(per_key_limit=2)
        bufs = [pool.checkout(8, 4) for _ in range(5)]
        for b in bufs:
            pool.checkin(b)
        assert pool.stats()["pooled"] == 2

    def test_two_engines_share_one_pool(self, tmp_path):
        # the sub-linear residency claim, concretely: two resident
        # engines served in turn allocate ONE buffer per (bucket, width)
        # between them, not one each
        pool = SharedStagingPool()
        paths = []
        for i, seed in enumerate((1, 7)):
            gen = tiny_generator(seed=seed)
            p = str(tmp_path / f"g{i}.zip")
            write_model(p, gen, gen.init(), save_updater=False)
            paths.append(p)
        engines = [
            ServingEngine.from_checkpoints(
                generator=p, buckets=(4,), export_gauge=False,
                staging_pool=pool)
            for p in paths
        ]
        z = np.random.default_rng(0).random((3, Z), dtype=np.float32)
        for _ in range(4):
            for eng in engines:
                eng.run("sample", z)
        assert pool.stats()["allocated_total"] == 1
        # staged assembly through the shared pool stays bit-exact vs the
        # host-assembly oracle per engine
        for eng in engines:
            np.testing.assert_array_equal(eng.run("sample", z),
                                          eng.run_host("sample", z))


# ===========================================================================
# registry: residency budget, eviction, re-warm, routing
# ===========================================================================

class _FakeEngine:
    """Engine-shaped fake: async dispatch/finalize, no jax."""

    def __init__(self, name, generation=None, fail=False):
        self.name = name
        self.generation = generation
        self.warmed = True
        self.warm_failed = False
        self.kinds = ("sample",)
        self._fail = fail

    def warmup(self, background=False):
        return {}

    def input_width(self, kind):
        return Z

    def dispatch(self, kind, rows_list):
        if self._fail:
            raise RuntimeError("engine down")
        return types.SimpleNamespace(
            lane=0, rows=[np.asarray(r) for r in rows_list])

    def finalize(self, flight):
        return np.concatenate(flight.rows) * 2.0


def fake_registry(budget=2, builds=None, **kw):
    builds = builds if builds is not None else []

    def build(variant):
        builds.append(variant.name)
        return _FakeEngine(variant.name,
                           generation=variant.generation)

    kw.setdefault("batcher_kwargs",
                  {"max_latency": 0.0, "default_timeout": 2.0})
    reg = MuxRegistry(buckets=(1, 8), budget=budget, build=build, **kw)
    reg._test_builds = builds
    return reg


class TestMuxRegistry:
    def test_add_routes_and_serves(self):
        reg = fake_registry()
        reg.add("a", bundle_path="/a", weight=1.0, generation=3)
        name, batcher = reg.route("k1")
        assert name == "a"
        r = batcher.submit("sample", np.ones((2, Z), np.float32))
        assert r.ok and r.data.shape == (2, Z)
        assert reg.variant("a").generation == 3
        reg.close()

    def test_budget_evicts_least_weighted_to_cold_manifest(self):
        reg = fake_registry(budget=2)
        reg.add("heavy", bundle_path="/h", weight=0.9)
        reg.add("lite", bundle_path="/l", weight=0.1)
        reg.add("new", bundle_path="/n", weight=0.5)
        assert sorted(reg.resident_names()) == ["heavy", "new"]
        lite = reg.variant("lite")
        assert lite.state == "cold"
        assert lite.engine is None and lite.batcher is None
        assert [e["event"] for e in reg.events].count("demote") == 1
        reg.close()

    def test_demoted_variant_rewarms_on_weight(self):
        reg = fake_registry(budget=1)
        reg.add("a", bundle_path="/a", weight=1.0)
        reg.add("b", bundle_path="/b", weight=0.1)  # evicts a or b
        builds_before = list(reg._test_builds)
        cold = [n for n in reg.names() if reg.variant(n).state == "cold"]
        assert len(cold) == 1
        # raising the cold variant's weight re-warms it through the
        # build path (and the budget demotes the other one)
        reg.set_weight(cold[0], 5.0)
        assert reg.variant(cold[0]).state == "resident"
        assert reg._test_builds == builds_before + cold
        reg.close()

    def test_demote_closes_batcher_and_sheds_cleanly(self):
        reg = fake_registry(budget=2)
        reg.add("a", bundle_path="/a", weight=1.0)
        _, batcher = reg.route("k")
        assert reg.demote("a") is True
        # the detached batcher is closed: a straggler submit sheds with
        # an explicit overloaded result, never hangs or errors
        r = batcher.submit("sample", np.ones((1, Z), np.float32))
        assert r.status == "overloaded"
        assert reg.demote("a") is False  # already cold

    def test_engine_only_variant_is_never_demoted(self):
        reg = fake_registry(budget=1)
        reg.add("pinned", engine=_FakeEngine("pinned"), weight=0.1)
        reg.add("other", bundle_path="/o", weight=9.0)
        # over budget, but the pinned variant has no cold manifest to
        # re-warm from — the bundle-backed one is demoted instead even
        # though it carries more weight... unless it is the newcomer:
        # the newcomer is protected, so the registry stays over budget
        assert "pinned" in reg.resident_names()
        reg.close()

    def test_route_falls_back_past_cold_variants_and_counts(self):
        reg = fake_registry(budget=2)
        reg.add("a", bundle_path="/a", weight=1.0)
        reg.add("b", bundle_path="/b", weight=1.0)
        reg.add("c", bundle_path="/c", weight=1.0)  # one of them demoted
        cold = [n for n in reg.names() if reg.variant(n).state == "cold"]
        assert len(cold) == 1
        resident = set(reg.resident_names())
        for i in range(60):
            name, _ = reg.route(f"k{i}")
            assert name in resident
        reg.close()

    def test_adopt_records_event_and_budget_applies(self):
        reg = fake_registry(budget=1)
        reg.add("a", bundle_path="/a", weight=1.0)
        reg.adopt("b", _FakeEngine("b", generation=9), bundle_path="/b")
        assert [e["event"] for e in reg.events][-1] == "adopt"
        # newcomer protected; "a" (demotable) was evicted
        assert reg.resident_names() == ["b"]
        assert reg.variant("b").generation == 9
        reg.close()

    def test_duplicate_name_rejected(self):
        reg = fake_registry()
        reg.add("a", bundle_path="/a")
        with pytest.raises(ValueError):
            reg.add("a", bundle_path="/a2")

    def test_primary_is_highest_weighted_resident(self):
        reg = fake_registry(budget=3)
        reg.add("a", bundle_path="/a", weight=0.2)
        reg.add("b", bundle_path="/b", weight=0.8)
        assert reg.primary_name() == "b"
        assert reg.reference_engine().name == "b"
        assert reg.max_generation() is None
        reg.close()

    def test_snapshot_shape(self):
        reg = fake_registry()
        reg.add("a", bundle_path="/a", weight=1.0, cost=4.0)
        snap = reg.snapshot()
        v = snap["variants"]["a"]
        assert v["resident"] and v["cost"] == 4.0 and v["weight"] == 1.0
        assert snap["resident"] == 1 and snap["budget"] == 2
        assert "staging_pool" in snap
        reg.close()


# ===========================================================================
# ramp controller
# ===========================================================================

class TestRampController:
    def _registry(self):
        reg = fake_registry(budget=8)
        reg.add("inc", bundle_path="/i", weight=0.9)
        reg.add("can", bundle_path="/c", weight=0.0)
        return reg

    def test_walks_stages_and_completes(self):
        reg = self._registry()
        ramp = RampController(reg, "can", stages=(0.01, 0.5, 1.0),
                              hold_ticks=2, health=lambda: True)
        ramp.start()
        shares = reg.splitter.shares()
        assert abs(shares["can"] - 0.01) < 1e-9
        assert ramp.tick() == "ramping"  # streak 1/2
        assert ramp.tick() == "ramping"  # advance -> 0.5
        assert abs(reg.splitter.shares()["can"] - 0.5) < 1e-9
        ramp.tick()
        assert ramp.tick() == "ramping"  # advance -> 1.0
        ramp.tick()
        assert ramp.tick() == "complete"
        # completion IS the primary election: candidate takes all traffic
        assert reg.splitter.shares() == {"can": 1.0}
        assert reg.primary_name() == "can"
        reg.close()

    def test_rollback_on_burn_restores_base_weights(self):
        reg = self._registry()
        healthy = {"v": True}
        ramp = RampController(reg, "can", stages=(0.1, 0.5, 1.0),
                              hold_ticks=1,
                              health=lambda: healthy["v"])
        ramp.start()
        assert ramp.tick() == "ramping"  # -> 0.5
        healthy["v"] = False
        assert ramp.tick() == "rolled_back"
        assert ramp.rollbacks == 1
        weights = reg.splitter.weights()
        assert weights["can"] == 0.0
        assert weights["inc"] == 0.9  # the pre-ramp weight, exactly
        # a rolled-back ramp is inert
        assert ramp.tick() == "rolled_back"
        reg.close()

    def test_no_data_holds_neither_advance_nor_rollback(self):
        reg = self._registry()
        ramp = RampController(reg, "can", stages=(0.1, 1.0), hold_ticks=1,
                              health=lambda: None)
        ramp.start()
        for _ in range(5):
            assert ramp.tick() == "ramping"
        assert abs(reg.splitter.shares()["can"] - 0.1) < 1e-9
        assert ramp.rollbacks == 0
        reg.close()

    def test_health_from_tracker_three_values(self):
        clock = {"t": 100.0}
        tracker = SLOTracker(
            SLOConfig(fast_window_s=10.0, slow_window_s=60.0),
            clock=lambda: clock["t"],
            metric_prefix="mux", labels={"model": "can"})
        health = health_from_tracker(tracker)
        assert health() is None  # empty windows: no data, hold
        for _ in range(20):
            tracker.record(True, 0.01)
        assert health() is True
        for _ in range(20):
            tracker.record(False)
        assert health() is False

    def test_stage_validation(self):
        reg = self._registry()
        with pytest.raises(ValueError):
            RampController(reg, "can", stages=())
        with pytest.raises(ValueError):
            RampController(reg, "can", stages=(0.5, 0.1))
        with pytest.raises(ValueError):
            RampController(reg, "can", stages=(0.0, 1.0))
        with pytest.raises(ValueError):
            RampController(reg, "can", hold_ticks=0)
        reg.close()


# ===========================================================================
# per-model brownout tiering
# ===========================================================================

class TestPerModelBrownout:
    def _service(self, budget=4):
        reg = fake_registry(budget=budget)
        reg.add("heavy", bundle_path="/h", cost=4.0, weight=0.5)
        reg.add("mid", bundle_path="/m", cost=2.0, weight=0.3)
        reg.add("lite", bundle_path="/l", cost=1.0, weight=0.2)
        return MuxService(reg)

    def test_shed_order_is_most_expensive_first(self):
        svc = self._service()
        assert svc._shed_set() == set()
        svc.set_brownout(1)
        assert svc._shed_set() == {"heavy"}
        svc.set_brownout(2)
        assert svc._shed_set() == {"heavy", "mid"}
        # the cheapest variant NEVER sheds: level clamps at N-1
        assert svc.set_brownout(99) == 2
        assert "lite" not in svc._shed_set()
        svc.close()

    def test_browned_out_variant_sheds_with_honest_503(self):
        svc = self._service()
        svc.set_brownout(1)
        code, body = svc.handle(
            "POST", "/v1/sample",
            {"data": [[0.1] * Z], "model": "heavy"})
        assert code == 503
        assert body["status"] == "overloaded"
        assert "brownout" in body["error"] and body["model"] == "heavy"
        # the cheap variant keeps serving through the brownout
        code, body = svc.handle(
            "POST", "/v1/sample",
            {"data": [[0.1] * Z], "model": "lite"})
        assert code == 200 and body["model"] == "lite"
        svc.close()

    def test_sheds_feed_per_model_counters_and_slo(self):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        svc = self._service()
        svc.set_brownout(1)
        for i in range(5):
            code, _ = svc.handle(
                "POST", "/v1/sample",
                {"data": [[0.1] * Z], "model": "heavy"})
            assert code == 503
        snap = get_registry().snapshot()
        series = {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in snap["mux_brownout_sheds_total"]["series"]}
        assert series[(("model", "heavy"),)] == 5.0
        # the shed IS an availability event for the shed model
        assert svc.tracker_for("heavy").snapshot()["totals"]["failed"] == 5
        svc.close()

    def test_controller_hysteresis(self):
        ctl = BrownoutController(threshold=0.5, enter_ticks=2,
                                 exit_ticks=2)
        level = 0
        assert ctl.tick(0.9, level, 2) == 0    # hot 1/2
        level = ctl.tick(0.9, level, 2)
        assert level == 1                       # entered
        level = ctl.tick(0.9, level, 2)
        level = ctl.tick(0.9, level, 2)
        assert level == 2                       # escalated (capped)
        assert ctl.tick(0.9, level, 2) == 2     # at max: holds
        assert ctl.tick(0.1, level, 2) == 2     # calm 1/2
        level = ctl.tick(0.1, level, 2)
        assert level == 1                       # released one tier
        assert ctl.tick(float("nan"), level, 2) == 1  # no data: hold
        ctl2 = BrownoutController()
        assert ctl2.tick(float("nan"), 0, 2) == 0

    def test_shed_set_ignores_zero_weight_variants(self):
        # review-caught: ranking by cost alone let a tier shed the ONLY
        # traffic-carrying variant (a total outage dressed as
        # degradation) when the cheap siblings carried zero weight —
        # the shed set must rank WEIGHTED variants only, re-clamped
        # against the current weights per request
        reg = fake_registry(budget=4)
        reg.add("heavy", bundle_path="/h", cost=4.0, weight=1.0)
        reg.add("adopted", bundle_path="/a", cost=1.0, weight=0.0)
        svc = MuxService(reg)
        # one weighted variant: no tier may silence it
        assert svc.set_brownout(1) == 0
        assert svc._shed_set() == set()
        code, body = svc.handle(
            "POST", "/v1/sample", {"data": [[0.1] * Z], "key": "k"})
        assert code == 200 and body["model"] == "heavy"
        # the zero-weight variant gaining weight re-opens the tier —
        # and a weight change AFTER the level was set re-clamps
        reg.set_weight("adopted", 1.0)
        svc.set_brownout(1)
        assert svc._shed_set() == {"heavy"}
        reg.set_weight("adopted", 0.0)
        assert svc._shed_set() == set()
        svc.close()

    def test_rollback_rewarms_a_budget_evicted_incumbent(self):
        # review-caught: rollback restored weights with warm=False, so
        # an incumbent the residency budget evicted mid-ramp stayed
        # cold-but-weighted forever (every assignment a fallback)
        reg = fake_registry(budget=2)
        reg.add("heavy", bundle_path="/h", weight=0.9)
        reg.add("lite", bundle_path="/l", weight=0.1)
        reg.adopt("cand", _FakeEngine("cand"), bundle_path="/c")
        # the adoption evicted the least-weighted incumbent
        assert reg.variant("lite").state == "cold"
        ramp = RampController(reg, "cand", stages=(0.5, 1.0),
                              hold_ticks=1,
                              health=lambda: False)
        ramp.start()
        assert ramp.tick() == "rolled_back"
        weights = reg.splitter.weights()
        assert weights == {"heavy": 0.9, "lite": 0.1, "cand": 0.0}
        # the weighted incumbent came BACK (cand, now weightless and
        # demotable via its manifest, was evicted in its place)
        assert reg.variant("lite").state == "resident"
        reg.close()

    def test_queue_gauge_zeroed_after_demote(self):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        reg = fake_registry(budget=2)
        reg.add("a", bundle_path="/a", weight=1.0)
        svc = MuxService(reg)
        svc._pressure()
        reg.demote("a")
        svc._pressure()
        snap = get_registry().snapshot()
        series = {s["labels"]["model"]: s["value"]
                  for s in snap["mux_queue_depth"]["series"]}
        assert series["a"] == 0.0
        svc.close()

    def test_pressure_drives_level_through_control_tick(self):
        svc = self._service()
        svc._brownout_auto = BrownoutController(
            threshold=0.5, enter_ticks=1, exit_ticks=1)
        # force pressure: shrink a batcher queue and stuff it — simpler
        # to monkeypatch the pressure reading itself
        svc._pressure = lambda: 0.9
        svc.control_tick()
        assert svc.brownout_level == 1
        svc._pressure = lambda: 0.0
        svc.control_tick()
        assert svc.brownout_level == 0
        svc.close()


# ===========================================================================
# mux service end-to-end over real engines
# ===========================================================================

@pytest.fixture(scope="module")
def bundles(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mux_bundles")
    out = {}
    for name, (seed, gen_number) in (
            ("heavy", (1, 0)), ("lite", (7, 1)), ("cand", (9, 2))):
        d = str(tmp / name)
        write_bundle(d, gen_seed=seed, generation=gen_number)
        out[name] = d
    return out


@pytest.fixture()
def real_service(bundles):
    reg = MuxRegistry(
        buckets=(1, 8), budget=3,
        batcher_kwargs={"max_latency": 0.001, "default_timeout": 5.0})
    reg.add("heavy", bundle_path=bundles["heavy"], cost=4.0, weight=0.9)
    reg.add("lite", bundle_path=bundles["lite"], cost=1.0, weight=0.1)
    svc = MuxService(reg)
    yield svc
    svc.close()


class TestMuxServiceEndToEnd:
    def test_split_serves_zero_lost_and_deterministic(self, real_service):
        svc = real_service
        rng = np.random.default_rng(0)
        first_pass = {}
        for i in range(120):
            key = f"user-{i % 40}"  # keys repeat: stickiness observable
            rows = rng.random((2, Z), dtype=np.float32)
            code, body = svc.handle(
                "POST", "/v1/sample", {"data": rows.tolist(), "key": key})
            assert code == 200, body
            assert body["status"] == "ok"
            assert len(body["data"]) == 2
            assert len(body["data"][0]) == FEAT
            model = body["model"]
            assert first_pass.setdefault(key, model) == model
        # both variants saw traffic at 90/10 over 40 distinct keys —
        # and the split agrees with the splitter's own assignment
        expected = {k: svc.registry.splitter.assign(k)
                    for k in first_pass}
        assert first_pass == expected
        assert set(first_pass.values()) == {"heavy", "lite"}

    def test_restart_determinism_at_the_service_level(self, bundles):
        # the satellite, end-to-end: a REBUILT service (fresh registry,
        # fresh engines — a restarted worker) routes every key to the
        # same variant at the same weights
        def build():
            reg = MuxRegistry(
                buckets=(1, 8), budget=2,
                batcher_kwargs={"max_latency": 0.0,
                                "default_timeout": 5.0})
            reg.add("heavy", bundle_path=bundles["heavy"], weight=0.7)
            reg.add("lite", bundle_path=bundles["lite"], weight=0.3)
            return MuxService(reg)

        keys = [f"session-{i}" for i in range(30)]
        row = [[0.5] * Z]
        assignments = []
        for _ in range(2):
            svc = build()
            got = {}
            for key in keys:
                code, body = svc.handle(
                    "POST", "/v1/sample", {"data": row, "key": key})
                assert code == 200
                got[key] = body["model"]
            assignments.append(got)
            svc.close()
        assert assignments[0] == assignments[1]

    def test_metrics_keep_autoscaler_schema(self, real_service):
        m = real_service.metrics()
        # the fleet autoscaler's pressure signal reads these exact keys
        # off any worker — mux or singleton (docs/FLEET.md)
        assert isinstance(m["queue_depth"], int)
        assert isinstance(m["pipeline"]["in_flight"], int)
        assert m["generation"] == 0  # the primary's (heavy) generation
        assert m["draining"] is False
        assert set(m["mux"]["per_variant"]) == {"heavy", "lite"}

    def test_healthz_and_mux_status(self, real_service):
        code, h = real_service.handle("GET", "/healthz")
        assert code == 200 and h["status"] == "ok"
        assert h["primary"] == "heavy"
        assert set(h["variants"]) == {"heavy", "lite"}
        assert abs(h["shares"]["heavy"] - 0.9) < 1e-9
        assert h["brownout"]["active"] is False
        code, s = real_service.handle("GET", "/mux/status")
        assert code == 200 and s["primary"] == "heavy"

    def test_per_model_series_in_registry(self, real_service):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        for i in range(4):
            real_service.handle(
                "POST", "/v1/sample",
                {"data": [[0.2] * Z], "model": "lite"})
        snap = get_registry().snapshot()
        fam = snap["mux_requests_total"]["series"]
        lite_ok = [s for s in fam
                   if s["labels"].get("model") == "lite"
                   and s["labels"].get("status") == "ok"]
        assert lite_ok and lite_ok[0]["value"] >= 4.0

    def test_http_round_trip_with_prom_scrape(self, real_service):
        import urllib.request

        server = make_server(real_service, port=0)
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/sample",
                data=json.dumps(
                    {"data": [[0.3] * Z], "key": "http-1"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            assert body["status"] == "ok" and body["model"] in (
                "heavy", "lite")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?format=prom",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "mux_requests_total" in text
            assert 'model="' in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=10) as resp:
                h = json.loads(resp.read())
            assert h["status"] == "ok"
        finally:
            server.shutdown()
            server.server_close()

    def test_bad_requests(self, real_service):
        svc = real_service
        assert svc.handle("POST", "/v1/sample", {})[0] == 400
        assert svc.handle("POST", "/v1/sample",
                          {"data": [[1.0] * (Z + 1)]})[0] == 400
        assert svc.handle("POST", "/v1/sample",
                          {"data": [[1.0] * Z], "key": 7})[0] == 400
        assert svc.handle("POST", "/v1/nope",
                          {"data": [[1.0] * Z]})[0] == 404
        assert svc.handle("POST", "/v1/sample",
                          {"data": [[1.0] * Z],
                           "model": "ghost"})[0] == 404
        assert svc.handle("GET", "/nope", None)[0] == 404

    def test_ramp_over_real_engines(self, real_service, bundles):
        svc = real_service
        svc.registry.add("cand", bundle_path=bundles["cand"], cost=1.0,
                         weight=0.0)
        ramp = svc.start_ramp("cand", stages=(0.5, 1.0), hold_ticks=1,
                              health=lambda: True)
        assert svc.registry.variant("cand").state == "resident"
        code, body = svc.handle(
            "POST", "/v1/sample", {"data": [[0.4] * Z], "model": "cand"})
        assert code == 200
        ramp.tick()
        ramp.tick()
        assert ramp.state == "complete"
        assert svc.registry.primary_name() == "cand"


# ===========================================================================
# the reload plane feeds the registry (registry-mode ReloadController)
# ===========================================================================

class TestReloadFeedsRegistry:
    def test_adopts_candidates_instead_of_swapping(self, tmp_path):
        from gan_deeplearning4j_tpu.deploy import ReloadController
        from gan_deeplearning4j_tpu.deploy.watcher import StoreWatcher
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        store = CheckpointStore(str(tmp_path / "store"))

        def publish(seed):
            number = store.next_number()
            return store.publish(
                lambda d: write_bundle(d, gen_seed=seed,
                                       generation=number),
                step=number, extra={"kind": "serving"})

        g0 = publish(1)
        reg = MuxRegistry(
            buckets=(1, 4), budget=2,
            batcher_kwargs={"max_latency": 0.0, "default_timeout": 5.0})
        ctl = ReloadController(
            None, StoreWatcher(store=store), registry=reg,
            adopt_cost=2.0)
        # bootstrap: the first valid generation is adopted ungated (no
        # incumbent to compare against), resident at weight 0
        status = ctl.poll_now()
        assert status["mode"] == "registry"
        assert status["adopted"] == 1
        name0 = f"gen-{g0.number}"
        assert reg.names() == [name0]
        assert reg.variant(name0).state == "resident"
        assert reg.splitter.weights()[name0] == 0.0
        assert reg.variant(name0).cost == 2.0
        # a newer generation is adopted as a SECOND variant — nothing
        # swapped, nothing drained, the incumbent untouched
        reg.set_weight(name0, 1.0)
        g1 = publish(5)
        ctl.poll_now()
        name1 = f"gen-{g1.number}"
        assert sorted(reg.names()) == sorted([name0, name1])
        assert reg.variant(name1).state == "resident"
        assert reg.primary_name() == name0  # weight still rules
        assert ctl.status()["adopted"] == 2
        # nothing newer: idle cycle
        assert ctl.poll_now()["state"] == "idle"
        assert ctl.status()["adopted"] == 2
        reg.close()

    def test_candidate_dropping_kinds_rejected_not_adopted(self, tmp_path):
        from gan_deeplearning4j_tpu.deploy import ReloadController
        from gan_deeplearning4j_tpu.deploy.watcher import StoreWatcher
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        store = CheckpointStore(str(tmp_path / "store"))

        def publish(writer):
            number = store.next_number()
            return store.publish(writer, step=number,
                                 extra={"kind": "serving"})

        publish(lambda d: _full_bundle(d, generation=store.next_number()))
        reg = MuxRegistry(
            buckets=(1, 4), budget=2,
            batcher_kwargs={"max_latency": 0.0, "default_timeout": 5.0})
        ctl = ReloadController(None, StoreWatcher(store=store),
                               registry=reg)
        ctl.poll_now()
        assert len(reg.names()) == 1
        reg.set_weight(reg.names()[0], 1.0)
        # generator-only candidate drops the classify kind the primary
        # serves: config mismatch — rejected, never adopted
        publish(lambda d: write_bundle(d, gen_seed=3,
                                       generation=store.next_number()))
        ctl.poll_now()
        assert len(reg.names()) == 1
        assert ctl.status()["rejected"] == 1
        reg.close()


def _full_bundle(directory, *, generation):
    """Bundle with generator AND classifier (both kinds served)."""
    os.makedirs(directory, exist_ok=True)
    gen, cv = tiny_generator(seed=2), tiny_classifier(seed=4)
    write_model(os.path.join(directory, "gen.zip"), gen, gen.init(),
                save_updater=False)
    write_model(os.path.join(directory, "cv.zip"), cv, cv.init(),
                save_updater=False)
    with open(os.path.join(directory, "serving.json"), "w") as fh:
        json.dump({"format_version": 1, "generator": "gen.zip",
                   "classifier": "cv.zip", "feature_vertex": "feat_1",
                   "generation": generation}, fh)


# ===========================================================================
# the drill (slow — the campaign gate's shape)
# ===========================================================================

@pytest.mark.slow
def test_mux_drill_smoke(tmp_path):
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_drill.py"),
         "--smoke", "--mux", "--workdir", str(tmp_path / "work"),
         "--output", str(tmp_path / "mux.json")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "GDT_COMPILATION_CACHE": "off"},
        timeout=560,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    with open(tmp_path / "mux.json") as fh:
        payload = json.load(fh)
    assert payload["ok"] is True
    assert payload["invariants"]["zero_lost"]
    assert payload["invariants"]["brownout_sheds_expensive_first"]


# ===========================================================================
# fleet merge: the model/generation label pass-through satellite
# ===========================================================================

class TestMergeMemberLabels:
    def test_generation_label_keeps_per_model_series_apart(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
        )

        def worker_snap(n):
            return {"serve_requests_total": {
                "type": "counter", "help": "",
                "series": [{"labels": {"kind": "sample", "status": "ok"},
                            "value": float(n)}]}}

        # WITHOUT the member labels the two workers' series collapse
        merged = merge_snapshots({"w0": worker_snap(10),
                                  "w1": worker_snap(3)})
        series = merged["serve_requests_total"]["series"]
        assert len(series) == 1 and series[0]["value"] == 13.0
        # WITH them, one series per generation — per-model truth kept
        merged = merge_snapshots(
            {"w0": worker_snap(10), "w1": worker_snap(3)},
            member_labels={"w0": {"generation": "4"},
                           "w1": {"generation": "7"}})
        series = {s["labels"]["generation"]: s["value"]
                  for s in merged["serve_requests_total"]["series"]}
        assert series == {"4": 10.0, "7": 3.0}

    def test_member_labels_never_override_series_labels(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
        )

        snap = {"mux_requests_total": {
            "type": "counter", "help": "",
            "series": [{"labels": {"model": "lite"}, "value": 2.0}]}}
        merged = merge_snapshots(
            {"w0": snap}, member_labels={"w0": {"model": "WRONG",
                                                "generation": "9"}})
        s = merged["mux_requests_total"]["series"][0]
        assert s["labels"]["model"] == "lite"  # the worker's label wins
        assert s["labels"]["generation"] == "9"

    def test_gauges_get_member_labels_and_worker(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
        )

        snap = {"serve_queue_depth": {
            "type": "gauge", "help": "",
            "series": [{"labels": {}, "value": 3.0}]}}
        merged = merge_snapshots(
            {"w0": snap}, member_labels={"w0": {"generation": "4"}})
        s = merged["serve_queue_depth"]["series"][0]
        assert s["labels"] == {"generation": "4", "worker": "w0"}


# ===========================================================================
# per-model alerting (telemetry/alerts.py, PR 15)
# ===========================================================================

class TestMuxAlerts:
    def _service_with_alerts(self):
        from gan_deeplearning4j_tpu.telemetry.alerts import (
            AlertManager,
            default_mux_rules,
        )

        reg = fake_registry(budget=4)
        reg.add("heavy", bundle_path="/h", cost=4.0, weight=0.8)
        reg.add("lite", bundle_path="/l", cost=1.0, weight=0.2)
        mgr = AlertManager(default_mux_rules())
        return MuxService(reg, alerts=mgr), mgr

    def test_model_burn_rule_scopes_per_variant(self):
        # fail one variant's SLI stream hard: only ITS alert instance
        # fires — the per-model scoping falls out of the labeled series
        svc, mgr = self._service_with_alerts()
        for _ in range(50):
            svc.tracker_for("heavy").record(False)
            svc.tracker_for("lite").record(True, 0.01)
        for _ in range(6):
            svc.control_tick()
        firing = [e for e in mgr.active() if e["state"] == "firing"]
        assert firing, mgr.active()
        assert {e["labels"].get("model") for e in firing} == {"heavy"}
        assert {e["alert"] for e in firing} == {"model_slo_burn"}
        # the surface answers on the mux routing table too
        code, body = svc.handle("GET", "/alerts")
        assert code == 200 and body["counts"]["firing"] >= 1
        code, hz = svc.handle("GET", "/healthz")
        assert hz["alerts"]["ok"] is False
        svc.close()

    def test_no_alert_plane_is_a_404_and_zero_cost(self):
        reg = fake_registry(budget=4)
        reg.add("only", bundle_path="/o", cost=1.0, weight=1.0)
        svc = MuxService(reg)
        code, body = svc.handle("GET", "/alerts")
        assert code == 404
        svc.control_tick()  # no evaluator to tick — must not crash
        svc.close()
