"""CLI entry-point tests — ``python -m gan_deeplearning4j_tpu``.

The reference's only entry point is ``main`` (dl4jGANComputerVision.java:94-101);
round 1 shipped a NameError in the post-training offline-eval block that no
test caught because nothing exercised ``main()``. These do.
"""

import os

import numpy as np
import pytest

from gan_deeplearning4j_tpu.__main__ import main


def _args(tmp_path, *extra):
    return [
        "--batch-size-train", "16",
        "--batch-size-pred", "16",
        "--num-iterations", "2",
        "--latent-grid", "4",
        "--data-dir", str(tmp_path / "data"),
        "--output-dir", str(tmp_path / "out"),
        "--save-models", "false",
        *extra,
    ]


class TestMain:
    @pytest.mark.slow
    def test_main_mnist_end_to_end(self, tmp_path, capsys):
        """Full default path: synthetic data generation, training, offline
        eval (accuracy print + manifold PNG) — the block that crashed in
        round 1 with a NameError on ``re``."""
        rc = main(_args(tmp_path))
        assert rc == 0
        out = capsys.readouterr().out
        assert "Transfer-classifier accuracy:" in out
        assert "Manifold image:" in out
        png = tmp_path / "out" / "DCGAN_Generated_Images.png"
        assert png.exists() and png.stat().st_size > 0

    @pytest.mark.slow
    def test_main_picks_latest_export(self, tmp_path):
        """The offline eval must read the highest-index export."""
        rc = main(_args(tmp_path))
        assert rc == 0
        outdir = tmp_path / "out"
        exports = sorted(
            int(n.split("_")[-1].split(".")[0])
            for n in os.listdir(outdir)
            if n.startswith("mnist_out_")
        )
        assert exports == [1, 2]
        manifold = np.loadtxt(outdir / "mnist_out_2.csv", delimiter=",")
        assert manifold.shape == (16, 784)
