"""Checkpoint round-trip tests (ModelSerializer analog, SURVEY D12)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.models.dcgan_mnist import build_discriminator, build_generator
from gan_deeplearning4j_tpu.parallel import GraphTrainer
from gan_deeplearning4j_tpu.utils import ModelSerializer, read_model, write_model


def assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


class TestSerializer:
    def test_round_trip_params_updater_step(self, tmp_path):
        gen = build_generator()
        trainer = GraphTrainer(gen)
        state = trainer.init_state()
        path = os.path.join(tmp_path, "gen_model.zip")
        write_model(path, gen, state, save_updater=True)
        graph2, params, opt_state, step = read_model(path)
        assert_trees_equal(state.params, params)
        assert_trees_equal(state.opt_state, opt_state)
        assert step == 0
        # rebuilt graph runs the restored params
        z = jnp.zeros((2, 2))
        np.testing.assert_allclose(
            np.asarray(gen.output(state.params, z)),
            np.asarray(graph2.output(params, z)),
            rtol=1e-6,
        )

    def test_restore_resumes_training(self, tmp_path):
        dis = build_discriminator()
        trainer = GraphTrainer(dis, donate=False)
        state = trainer.init_state()
        path = os.path.join(tmp_path, "ck.zip")
        write_model(path, dis, state)
        restored = ModelSerializer.restore_train_state(path, trainer)
        z = jnp.ones((4, 784)) * 0.3
        y = jnp.ones((4, 1))
        s1, l1 = trainer.train_step(state, z, y)
        s2, l2 = trainer.train_step(restored, z, y)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        assert_trees_equal(s1.params, s2.params)
        assert int(s2.step) == 1

    def test_bf16_arrays_round_trip_with_dtype(self, tmp_path):
        """bf16 param storage (round-4): npz can't hold ml_dtypes extension
        types, so bf16 leaves travel as tagged uint16 bit patterns and must
        come back BIT-identical with the right dtype."""
        gen = build_generator()
        trainer = GraphTrainer(gen)
        state = trainer.init_state()
        bf16 = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            state,
        )
        path = os.path.join(tmp_path, "bf16.zip")
        write_model(path, gen, bf16, save_updater=True)
        _, params, opt_state, _ = read_model(path)
        for a, b in zip(
            jax.tree_util.tree_leaves(bf16.params), jax.tree_util.tree_leaves(params)
        ):
            assert b.dtype == a.dtype == jnp.bfloat16
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(bf16.opt_state),
            jax.tree_util.tree_leaves(opt_state),
        ):
            assert b.dtype == a.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_save_without_updater(self, tmp_path):
        gen = build_generator()
        params = gen.init()
        path = os.path.join(tmp_path, "p.zip")
        write_model(path, gen, params)
        _, params2, opt_state, _ = read_model(path)
        assert opt_state is None
        assert_trees_equal(params, params2)

    def test_overwrite_is_atomic_shape(self, tmp_path):
        gen = build_generator()
        params = gen.init()
        path = os.path.join(tmp_path, "p.zip")
        write_model(path, gen, params)
        write_model(path, gen, params)  # second save overwrites cleanly
        _, params2, _, _ = read_model(path)
        assert_trees_equal(params, params2)
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]

    def test_restore_without_updater_state(self, tmp_path):
        """The serving loader's read shape: topology + params only, even
        from a checkpoint that carries full updater state."""
        gen = build_generator()
        trainer = GraphTrainer(gen)
        state = trainer.init_state()
        path = os.path.join(tmp_path, "full.zip")
        write_model(path, gen, state, save_updater=True)
        graph2, params, opt_state, step = read_model(path, load_updater=False)
        assert opt_state is None
        assert_trees_equal(state.params, params)
        z = jnp.full((3, 2), 0.25)
        np.testing.assert_allclose(
            np.asarray(gen.output(state.params, z)),
            np.asarray(graph2.output(params, z)),
            rtol=1e-6,
        )

    def test_restore_in_fresh_process_without_defining_code(self, tmp_path):
        """A checkpoint is self-contained: a fresh interpreter that never
        imports the model-zoo builders restores topology + params and runs
        a forward pass — exactly what a serving replica does."""
        import subprocess
        import sys

        gen = build_generator()
        path = os.path.join(tmp_path, "gen.zip")
        write_model(path, gen, gen.init(), save_updater=False)
        expect = np.asarray(gen.output(gen.init(), jnp.zeros((2, 2))))
        script = (
            "import sys, numpy as np, jax.numpy as jnp\n"
            "from gan_deeplearning4j_tpu.utils.serializer import read_model\n"
            # forbid the defining code path: restoring must not need it
            "sys.modules['gan_deeplearning4j_tpu.models'] = None\n"
            "graph, params, opt, step = read_model(sys.argv[1])\n"
            "assert opt is None and step == 0\n"
            "out = np.asarray(graph.output(params, jnp.zeros((2, 2))))\n"
            "np.save(sys.argv[2], out)\n"
        )
        out_path = os.path.join(tmp_path, "fwd.npy")
        proc = subprocess.run(
            [sys.executable, "-c", script, path, out_path],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        np.testing.assert_allclose(np.load(out_path), expect, rtol=1e-6)

    def test_truncated_zip_rejected(self, tmp_path):
        """A killed writer must never let a reader half-load: truncated
        bytes raise ValueError (not a silent partial tree)."""
        gen = build_generator()
        path = os.path.join(tmp_path, "t.zip")
        write_model(path, gen, gen.init())
        data = open(path, "rb").read()
        for frac in (0.2, 0.9):
            bad = os.path.join(tmp_path, f"bad_{frac}.zip")
            with open(bad, "wb") as fh:
                fh.write(data[: int(len(data) * frac)])
            with pytest.raises(ValueError, match="corrupt|truncat|missing"):
                read_model(bad)

    def test_member_digest_mismatch_rejected(self, tmp_path):
        """Per-member content digests (resilience PR): a member whose bytes
        were swapped for OTHER valid bytes — same zip structure, CRCs
        consistent — still fails the digest check from meta.json. This is
        the corruption class a truncation check can never see."""
        import json
        import zipfile

        gen = build_generator()
        path = os.path.join(tmp_path, "p.zip")
        write_model(path, gen, gen.init())
        with zipfile.ZipFile(path) as zf:
            assert "member_digests" in json.loads(zf.read("meta.json"))
        bad = os.path.join(tmp_path, "tampered.zip")
        with zipfile.ZipFile(path) as zin, zipfile.ZipFile(bad, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "topology.json":
                    # a valid but different topology payload
                    data = data[:-1] + b" " + data[-1:]
                zout.writestr(name, data)
        with pytest.raises(ValueError, match="digest"):
            read_model(bad)

    def test_pre_digest_checkpoints_still_load(self, tmp_path):
        """Backward compatibility: a checkpoint written before
        member_digests existed (no key in meta.json) loads fine."""
        import json
        import zipfile

        gen = build_generator()
        params = gen.init()
        path = os.path.join(tmp_path, "p.zip")
        write_model(path, gen, params)
        old = os.path.join(tmp_path, "old.zip")
        with zipfile.ZipFile(path) as zin, zipfile.ZipFile(old, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "meta.json":
                    meta = json.loads(data)
                    del meta["member_digests"]
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        _, params2, _, _ = read_model(old)
        assert_trees_equal(params, params2)

    def test_garbage_file_rejected(self, tmp_path):
        bad = os.path.join(tmp_path, "junk.zip")
        with open(bad, "wb") as fh:
            fh.write(b"not a zip at all")
        with pytest.raises(ValueError, match="corrupt|truncat"):
            read_model(bad)

    def test_future_version_rejected(self, tmp_path):
        import json
        import zipfile

        gen = build_generator()
        params = gen.init()
        path = os.path.join(tmp_path, "p.zip")
        write_model(path, gen, params)
        bad = os.path.join(tmp_path, "bad.zip")
        with zipfile.ZipFile(path) as zin, zipfile.ZipFile(bad, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "meta.json":
                    meta = json.loads(data)
                    meta["format_version"] = 999
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        with pytest.raises(ValueError, match="newer"):
            read_model(bad)


class TestStateShards:
    """The mesh checkpoint plane's shard format (resilience/mesh.py):
    deterministic key partition, self-verifying per-shard zips, and the
    merge property elastic restore rests on."""

    def test_shard_keys_partition_is_exact(self):
        from gan_deeplearning4j_tpu.utils.serializer import shard_keys

        keys = [f"m/params/l{i}/w" for i in range(17)]
        for count in (1, 2, 4, 5):
            shards = [shard_keys(keys, k, count) for k in range(count)]
            merged = sorted(k for s in shards for k in s)
            assert merged == sorted(keys)  # disjoint AND covering
            # balanced: no shard more than one key heavier than another
            sizes = [len(s) for s in shards]
            assert max(sizes) - min(sizes) <= 1
        # deterministic regardless of input order
        assert shard_keys(reversed(keys), 1, 3) == shard_keys(keys, 1, 3)

    def test_shard_keys_validation(self):
        from gan_deeplearning4j_tpu.utils.serializer import shard_keys

        with pytest.raises(ValueError):
            shard_keys(["a"], 0, 0)
        with pytest.raises(ValueError):
            shard_keys(["a"], 2, 2)

    def test_shard_round_trip_including_bf16(self, tmp_path):
        from gan_deeplearning4j_tpu.utils.serializer import (
            read_state_shard,
            write_state_shard,
        )

        flat = {
            "dis/params/w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "dis/updater/w/cache": jnp.asarray([1.5, 2.5], jnp.bfloat16),
            "dis/step": np.int32(7),
        }
        path = os.path.join(tmp_path, "shard.zip")
        write_state_shard(path, flat, meta={"shard_index": 0,
                                            "shard_count": 2,
                                            "total_keys": 6})
        back, meta = read_state_shard(path)
        assert sorted(back) == sorted(flat)
        assert back["dis/updater/w/cache"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(back["dis/params/w"]), np.asarray(flat["dis/params/w"]))
        np.testing.assert_array_equal(
            np.asarray(back["dis/updater/w/cache"]).view(np.uint16),
            np.asarray(flat["dis/updater/w/cache"]).view(np.uint16))
        assert meta["shard_index"] == 0 and meta["shard_count"] == 2
        assert meta["total_keys"] == 6

    def test_shard_corruption_rejected(self, tmp_path):
        from gan_deeplearning4j_tpu.utils.serializer import (
            read_state_shard,
            write_state_shard,
        )

        path = os.path.join(tmp_path, "shard.zip")
        write_state_shard(path, {"x": np.zeros(64, np.float32)}, meta={})
        import json
        import zipfile

        bad = os.path.join(tmp_path, "bad.zip")
        with zipfile.ZipFile(path) as zin, zipfile.ZipFile(bad, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "arrays.npz":
                    data = data[:-1] + bytes([data[-1] ^ 0xFF])
                zout.writestr(name, data)
        with pytest.raises(ValueError, match="digest"):
            read_state_shard(bad)
        # truncation of the zip container itself
        with open(path, "rb") as fh:
            blob = fh.read()
        torn = os.path.join(tmp_path, "torn.zip")
        with open(torn, "wb") as fh:
            fh.write(blob[: len(blob) // 2])
        with pytest.raises(ValueError):
            read_state_shard(torn)
        # future format version refused
        future = os.path.join(tmp_path, "future.zip")
        with zipfile.ZipFile(path) as zin, \
                zipfile.ZipFile(future, "w") as zout:
            for name in zin.namelist():
                data = zin.read(name)
                if name == "meta.json":
                    meta = json.loads(data)
                    meta["format_version"] = 999
                    data = json.dumps(meta).encode()
                zout.writestr(name, data)
        with pytest.raises(ValueError, match="newer"):
            read_state_shard(future)
