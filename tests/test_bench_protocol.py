"""The bench's driver-facing output protocol (round-4 VERDICT item 1).

The contract that lost round 3 when unmet: at ANY instant the bench process
might be killed, its last stdout line must be a complete, parseable summary
JSON carrying the headline metric and one entry per requested config. These
tests pin the Reporter half of that contract (the measurement half is
exercised end-to-end by running ``bench.py`` itself — see BASELINE.md).
"""

import io
import json
import os
import sys
import time

import bench


def _lines(capsys_text):
    return [json.loads(l) for l in capsys_text.strip().splitlines()]


class TestReporter:
    def _reporter(self, keys=("1", "5"), baselines=None, t0=0.0):
        return bench.Reporter(
            list(keys),
            baselines if baselines is not None
            else {bench.CONFIG_META["1"][0]: 1000.0},
            None,
            t0,
        )

    def test_preliminary_line_is_complete_summary(self, capsys):
        r = self._reporter()
        r.emit()
        (line,) = _lines(capsys.readouterr().out)
        assert line["metric"] == bench.CONFIG_META["1"][0]
        assert line["value"] == 1000.0  # stale baseline stands in
        assert line["vs_baseline"] is None
        assert line["stale"] and line["preliminary"] and line["degraded"]
        assert len(line["results"]) == 2
        for res in line["results"]:
            assert res["stale"] and res["skipped"] == "not reached"

    def test_measured_result_takes_headline(self, capsys):
        r = self._reporter()
        r.diag.update(platform="tpu", device_kind="x", degraded=False)
        r.set_result("1", {"config": "1", "metric": bench.CONFIG_META["1"][0],
                           "value": 2000.0, "vs_baseline": 2.0, "mfu": 0.1})
        line = _lines(capsys.readouterr().out)[-1]
        assert line["value"] == 2000.0
        assert line["vs_baseline"] == 2.0
        assert "stale" not in line
        # the OTHER config still appears as a labeled placeholder
        by_cfg = {res["config"]: res for res in line["results"]}
        assert by_cfg["5"]["skipped"] == "not reached"
        assert by_cfg["1"]["value"] == 2000.0

    def test_every_emit_is_parseable_and_reemits_everything(self, capsys):
        r = self._reporter(keys=("1", "5", "2"))
        r.emit()
        r.set_result("5", {"config": "5", "metric": bench.CONFIG_META["5"][0],
                           "value": 7.0})
        r.set_result("2", r.stale_entry("2", "budget: 3s left"))
        lines = _lines(capsys.readouterr().out)
        assert len(lines) == 3  # one full summary per state change
        assert all(len(l["results"]) == 3 for l in lines)
        last = {res["config"]: res for res in lines[-1]["results"]}
        assert last["5"]["value"] == 7.0
        assert last["2"]["skipped"] == "budget: 3s left"

    def test_headline_falls_back_to_first_requested_config(self, capsys):
        r = self._reporter(keys=("5", "2"), baselines={})
        r.emit()
        (line,) = _lines(capsys.readouterr().out)
        assert line["metric"] == bench.CONFIG_META["5"][0]
        assert line["value"] is None  # no baseline for it either

    def test_json_file_mirrors_stdout(self, tmp_path, capsys):
        path = str(tmp_path / "bench.json")
        r = bench.Reporter(["1"], {}, path, 0.0)
        r.set_result("1", {"config": "1", "metric": bench.CONFIG_META["1"][0],
                           "value": 5.0})
        capsys.readouterr()
        with open(path) as fh:
            d = json.load(fh)
        assert d["results"][0]["value"] == 5.0
        assert "diagnostics" in d

    def _fat_result(self, key):
        """A measured result carrying every diagnostic the child can attach —
        the shape whose stdout serialization overflowed the driver's
        2,000-char tail in rounds 3-4."""
        metric, unit = bench.CONFIG_META[key]
        return {
            "config": key, "metric": metric, "unit": unit, "value": 87654.32,
            "vs_baseline": 1.234, "baseline_platform": "tpu",
            "baseline_window": 32, "mfu": 0.2762, "compute_dtype": "bf16",
            "flops_per_iter": 123456789012, "sec_per_iter": 0.001234,
            "iter_time_jitter": 0.0125, "timed_iters": 5000,
            "measured_seconds": 6.171, "device_loop_window": 128,
            "devices": 8, "degraded": False, "platform": "tpu",
            "device_kind": "TPU v5 lite",
            "f32_images_per_sec": 52220.39, "bf16_images_per_sec": 48000.11,
            "bf16_speedup_vs_f32": 0.919,
            "bf16_storage_images_per_sec": 56123.44,
            "bf16_storage_speedup_vs_f32": 1.075,
            "per_dispatch_images_per_sec": 31000.25,
        }

    def test_every_stdout_line_fits_the_driver_tail(self, capsys):
        # Round-5 VERDICT item 1: the driver keeps a 2,000-char stdout tail;
        # rounds 3-4 were parsed=null because the final line outgrew it.
        # Worst case: ALL configs measured with full diagnostics + errors.
        keys = list(bench.CONFIG_ORDER)
        r = bench.Reporter(keys, {}, None, 0.0)
        r.diag.update(platform="tpu", device_kind="TPU v5 lite", degraded=False)
        r.emit()
        for k in keys[:-1]:
            r.set_result(k, self._fat_result(k))
        r.set_result(keys[-1], {
            "config": keys[-1], "metric": bench.CONFIG_META[keys[-1]][0],
            "unit": bench.CONFIG_META[keys[-1]][1],
            "error": "RuntimeError: " + "x" * 500, "degraded": False,
        })
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == len(keys) + 1
        for line in lines:
            assert len(line) < bench.MAX_LINE_CHARS
            json.loads(line)  # and still parseable

    def test_stdout_rows_are_compact_but_json_rows_are_full(self, tmp_path, capsys):
        path = str(tmp_path / "bench.json")
        r = bench.Reporter(["1"], {}, path, 0.0)
        r.set_result("1", self._fat_result("1"))
        line = _lines(capsys.readouterr().out)[-1]
        (row,) = line["results"]
        # stdout: identity + value + regression signal + platform honesty only
        assert set(row) <= {"config", "value", "vs_baseline", "degraded",
                            "baseline_platform", "stale", "skipped", "error"}
        assert row["value"] == 87654.32 and row["vs_baseline"] == 1.234
        # artifact file: the full diagnostics survive
        with open(path) as fh:
            full = json.load(fh)["results"][0]
        assert full["mfu"] == 0.2762 and full["iter_time_jitter"] == 0.0125

    def test_compact_truncates_error_strings(self):
        row = bench.Reporter._compact({"config": "3", "error": "y" * 1000})
        assert len(row["error"]) <= 80

    def test_oversize_line_is_repaired_not_asserted(self, monkeypatch, capsys):
        # Round 6 (jaxlint JG003): the old guard was a bare assert — gone
        # under `python -O`. Now an oversize line loses tail rows but stays
        # parseable, keeps the headline, and records the surgery.
        monkeypatch.setattr(bench, "MAX_LINE_CHARS", 400)
        keys = list(bench.CONFIG_ORDER)
        r = bench.Reporter(keys, {}, None, 0.0)
        r.diag.update(platform="tpu", device_kind="TPU v5 lite", degraded=False)
        for k in keys:
            r.set_result(k, self._fat_result(k))
        lines = capsys.readouterr().out.strip().splitlines()
        assert all(len(l) < 400 for l in lines)
        last = json.loads(lines[-1])
        assert last["results_truncated"] >= 1
        assert last["value"] == 87654.32  # headline survives the surgery
        assert r.diag["stdout_truncation"]["rows_dropped"] >= 1


class TestBaselineNamespaces:
    """Round-5 VERDICT item 2: degraded runs get a real vs_baseline against
    the cpu namespace; ADVICE r4 medium: window mismatches are annotated."""

    BASE = {
        "_meta": {"capture_window": {bench.CONFIG_META["1"][0]: 32}},
        bench.CONFIG_META["1"][0]: 1000.0,
        "_platform_baselines": {"cpu": {bench.CONFIG_META["1"][0]: 50.0}},
    }

    def test_degraded_uses_cpu_namespace(self):
        r = {"metric": bench.CONFIG_META["1"][0], "value": 55.0}
        bench.annotate_vs_baseline(r, self.BASE, degraded=True)
        assert r["vs_baseline"] == 1.1
        assert r["baseline_platform"] == "cpu"

    def test_degraded_without_cpu_baseline_is_null(self):
        r = {"metric": bench.CONFIG_META["2"][0], "value": 55.0}
        bench.annotate_vs_baseline(r, self.BASE, degraded=True)
        assert r["vs_baseline"] is None

    def test_accelerator_never_compares_to_cpu_baseline(self):
        r = {"metric": bench.CONFIG_META["1"][0], "value": 2000.0,
             "device_loop_window": 128}
        bench.annotate_vs_baseline(r, self.BASE, degraded=False)
        assert r["vs_baseline"] == 2.0  # against 1000, not 50
        assert r["baseline_platform"] == "tpu"

    def test_window_mismatch_is_annotated(self):
        r = {"metric": bench.CONFIG_META["1"][0], "value": 2000.0,
             "device_loop_window": 128}
        bench.annotate_vs_baseline(r, self.BASE, degraded=False)
        assert r["baseline_window"] == 32  # captured-at protocol differs
        r2 = {"metric": bench.CONFIG_META["1"][0], "value": 2000.0,
              "device_loop_window": 32}
        bench.annotate_vs_baseline(r2, self.BASE, degraded=False)
        assert "baseline_window" not in r2

    def test_merge_routes_by_platform_and_stamps_window(self):
        results = [
            {"metric": "m_tpu", "value": 9.0, "degraded": False,
             "device_loop_window": 128},
            {"metric": "m_cpu", "value": 7.0, "degraded": True},
            {"metric": "m_stale", "value": 1.0, "stale": True},
            {"metric": "m_err", "value": 1.0, "error": "boom"},
        ]
        merged = bench.merge_baselines({"m_tpu": 5.0}, results)
        assert merged["m_tpu"] == 9.0
        assert merged["_meta"]["capture_window"]["m_tpu"] == 128
        assert merged["_platform_baselines"]["cpu"]["m_cpu"] == 7.0
        assert "m_cpu" not in merged  # CPU value never lands at top level
        assert "m_stale" not in merged and "m_err" not in merged

    def test_seeded_cpu_namespace_covers_every_config(self):
        # the committed file must keep the drill-seeded namespace intact
        # (2b seeded round 6 at its labeled cheap_shape) — EVERY config row
        # must carry a degraded-round regression signal
        b = bench.load_baselines()
        cpu = b.get("_platform_baselines", {}).get("cpu", {})
        for key in bench.CONFIG_ORDER:
            assert bench.CONFIG_META[key][0] in cpu, key


class TestQuietHostGuard:
    def test_lock_excludes_live_owner(self, tmp_path):
        path = str(tmp_path / "l.lock")
        a = bench.HostLock(path)
        assert a.acquire() is None
        b = bench.HostLock(path)
        err = b.acquire()
        assert err is not None and "held by live pid" in err
        a.release()
        assert b.acquire() is None
        b.release()

    def test_stale_lock_is_stolen(self, tmp_path):
        path = str(tmp_path / "l.lock")
        with open(path, "w") as fh:
            fh.write("999999999")  # no such pid
        a = bench.HostLock(path)
        assert a.acquire() is None
        a.release()

    def test_garbage_lockfile_is_stolen(self, tmp_path):
        path = str(tmp_path / "l.lock")
        with open(path, "w") as fh:
            fh.write("not-a-pid")
        assert bench.HostLock(path).acquire() is None

    # round-6 TOCTOU hardening: atomic publish, grace for empty pidfiles,
    # ownership-checked release, no temp droppings
    def test_empty_young_pidfile_counts_as_held(self, tmp_path):
        path = str(tmp_path / "l.lock")
        open(path, "w").close()  # a legacy writer between create and write
        err = bench.HostLock(path).acquire()
        assert err is not None and "being written" in err

    def test_empty_old_pidfile_is_stolen_atomically(self, tmp_path):
        path = str(tmp_path / "l.lock")
        open(path, "w").close()
        old = time.time() - 60
        os.utime(path, (old, old))
        lock = bench.HostLock(path)
        assert lock.acquire() is None
        with open(path) as fh:  # the steal never exposes an empty pidfile
            assert fh.read().strip() == str(os.getpid())
        lock.release()
        assert not os.path.exists(path)

    def test_release_leaves_a_stolen_lock_alone(self, tmp_path):
        path = str(tmp_path / "l.lock")
        a = bench.HostLock(path)
        assert a.acquire() is None
        with open(path, "w") as fh:
            fh.write("424242")  # someone judged us dead and took it
        a.release()
        assert os.path.exists(path)  # not ours anymore — must not unlink

    def test_acquire_cycle_leaves_no_temp_files(self, tmp_path):
        path = str(tmp_path / "l.lock")
        lock = bench.HostLock(path)
        assert lock.acquire() is None
        lock.release()
        assert os.listdir(str(tmp_path)) == []

    def test_load_status_thresholds(self, monkeypatch):
        monkeypatch.setattr(bench.os, "getloadavg", lambda: (2.5, 0, 0))
        s = bench.host_load_status(1.0)
        assert s["busy"] and s["load1"] == 2.5
        assert not bench.host_load_status(3.0)["busy"]


class FakeChild:
    """Scripted stand-in for bench.Child: serves a fixed event sequence,
    then times out forever."""

    def __init__(self, events):
        self.events = list(events)
        self.killed = False

    def next_event(self, timeout):
        return self.events.pop(0) if self.events else None

    def kill(self):
        self.killed = True


class TestRunChildStateMachine:
    def _run(self, monkeypatch, events, keys=("1", "5"), cpu=False):
        children = []

        def fake_child(k, mode, c, deadline):
            child = FakeChild(events)
            children.append(child)
            return child

        monkeypatch.setattr(bench, "Child", fake_child)
        r = bench.Reporter(list(keys), {}, None, 0.0)
        status, remaining = bench.run_child(
            list(keys), "full", cpu, ready_timeout=1.0, per_config_timeout=1.0,
            reporter=r, measure_deadline=bench.time.time() + 60,
        )
        return status, remaining, r, children

    def test_no_ready_returns_all_keys(self, monkeypatch, capsys):
        status, remaining, _, children = self._run(monkeypatch, [])
        assert status == "no_ready"
        assert remaining == ["1", "5"]
        assert children[0].killed

    def test_stall_after_one_result_blames_in_flight_config(self, monkeypatch, capsys):
        events = [
            {"event": "ready", "platform": "tpu", "device_kind": "v5",
             "devices": 1, "degraded": False},
            {"event": "result", "config": "1",
             "metric": bench.CONFIG_META["1"][0], "value": 10.0},
            # then silence: config 5 is in flight when the chip dies
        ]
        status, remaining, r, _ = self._run(monkeypatch, events)
        assert status == "stalled"
        assert remaining == ["5"]  # the hung config, first in remaining
        assert r.results["1"]["value"] == 10.0

    def test_accel_child_on_cpu_routes_to_fallback(self, monkeypatch, capsys):
        events = [{"event": "ready", "platform": "cpu", "device_kind": "cpu",
                   "devices": 1, "degraded": True}]
        status, remaining, r, children = self._run(monkeypatch, events, cpu=False)
        assert status == "came_up_cpu"
        assert remaining == ["1", "5"]
        assert children[0].killed
        # the summary must NOT claim a cpu platform came up as the accelerator
        assert r.diag.get("platform") != "cpu"

    def test_clean_completion(self, monkeypatch, capsys):
        events = [
            {"event": "ready", "platform": "tpu", "device_kind": "v5",
             "devices": 1, "degraded": False},
            {"event": "result", "config": "1",
             "metric": bench.CONFIG_META["1"][0], "value": 1.0},
            {"event": "result", "config": "5",
             "metric": bench.CONFIG_META["5"][0], "value": 2.0},
            {"event": "done"},
        ]
        status, remaining, r, _ = self._run(monkeypatch, events)
        assert status == "ok" and remaining == []
        assert set(r.results) == {"1", "5"}


class TestConfigTables:
    def test_config_tables_consistent(self):
        assert set(bench.CONFIG_ORDER) == set(bench.CONFIGS) == set(bench.CONFIG_META)
        assert bench.CONFIG_ORDER[0] == bench.HEADLINE == "1"

    def test_cheap_opts_stay_cheap(self):
        # the degraded path must never pick up expensive settings by accident:
        # XLA:CPU needs 70-140 s to COMPILE a scan program and tens of
        # seconds per call (measured round 4)
        assert bench.CHEAP_OPTS["scan_cap"] <= 1
        assert bench.CHEAP_OPTS["min_measured_s"] <= 1.0
        assert bench.CHEAP_OPTS["cheap"] is True
        assert bench.FULL_OPTS["cheap"] is False

    def test_axon_boot_vars_cover_the_relay_dial(self):
        assert "PALLAS_AXON_POOL_IPS" in bench.AXON_BOOT_VARS

    def test_full_window_is_the_run_loop_steady_state(self):
        # Three manually-coupled copies of the device-loop depth: the bench
        # measures run()'s steady state, so FULL_WINDOW must track
        # ExperimentConfig.loss_fetch_every's default, and scan_cap must not
        # silently clamp it (a FULL_WINDOW raise that forgets scan_cap would
        # report device_loop_window == FULL_WINDOW while measuring less).
        import dataclasses

        from gan_deeplearning4j_tpu.harness.config import ExperimentConfig

        default = {f.name: f.default for f in dataclasses.fields(ExperimentConfig)}
        assert bench.FULL_WINDOW == default["loss_fetch_every"]
        assert bench.FULL_OPTS["scan_cap"] >= bench.FULL_WINDOW
