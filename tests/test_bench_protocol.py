"""The bench's driver-facing output protocol (round-4 VERDICT item 1).

The contract that lost round 3 when unmet: at ANY instant the bench process
might be killed, its last stdout line must be a complete, parseable summary
JSON carrying the headline metric and one entry per requested config. These
tests pin the Reporter half of that contract (the measurement half is
exercised end-to-end by running ``bench.py`` itself — see BASELINE.md).
"""

import io
import json
import sys

import bench


def _lines(capsys_text):
    return [json.loads(l) for l in capsys_text.strip().splitlines()]


class TestReporter:
    def _reporter(self, keys=("1", "5"), baselines=None, t0=0.0):
        return bench.Reporter(
            list(keys),
            baselines if baselines is not None
            else {bench.CONFIG_META["1"][0]: 1000.0},
            None,
            t0,
        )

    def test_preliminary_line_is_complete_summary(self, capsys):
        r = self._reporter()
        r.emit()
        (line,) = _lines(capsys.readouterr().out)
        assert line["metric"] == bench.CONFIG_META["1"][0]
        assert line["value"] == 1000.0  # stale baseline stands in
        assert line["vs_baseline"] is None
        assert line["stale"] and line["preliminary"] and line["degraded"]
        assert len(line["results"]) == 2
        for res in line["results"]:
            assert res["stale"] and res["skipped"] == "not reached"

    def test_measured_result_takes_headline(self, capsys):
        r = self._reporter()
        r.diag.update(platform="tpu", device_kind="x", degraded=False)
        r.set_result("1", {"config": "1", "metric": bench.CONFIG_META["1"][0],
                           "value": 2000.0, "vs_baseline": 2.0, "mfu": 0.1})
        line = _lines(capsys.readouterr().out)[-1]
        assert line["value"] == 2000.0
        assert line["vs_baseline"] == 2.0
        assert "stale" not in line
        # the OTHER config still appears as a labeled placeholder
        by_cfg = {res["config"]: res for res in line["results"]}
        assert by_cfg["5"]["skipped"] == "not reached"
        assert by_cfg["1"]["value"] == 2000.0

    def test_every_emit_is_parseable_and_reemits_everything(self, capsys):
        r = self._reporter(keys=("1", "5", "2"))
        r.emit()
        r.set_result("5", {"config": "5", "metric": bench.CONFIG_META["5"][0],
                           "value": 7.0})
        r.set_result("2", r.stale_entry("2", "budget: 3s left"))
        lines = _lines(capsys.readouterr().out)
        assert len(lines) == 3  # one full summary per state change
        assert all(len(l["results"]) == 3 for l in lines)
        last = {res["config"]: res for res in lines[-1]["results"]}
        assert last["5"]["value"] == 7.0
        assert last["2"]["skipped"] == "budget: 3s left"

    def test_headline_falls_back_to_first_requested_config(self, capsys):
        r = self._reporter(keys=("5", "2"), baselines={})
        r.emit()
        (line,) = _lines(capsys.readouterr().out)
        assert line["metric"] == bench.CONFIG_META["5"][0]
        assert line["value"] is None  # no baseline for it either

    def test_json_file_mirrors_stdout(self, tmp_path, capsys):
        path = str(tmp_path / "bench.json")
        r = bench.Reporter(["1"], {}, path, 0.0)
        r.set_result("1", {"config": "1", "metric": bench.CONFIG_META["1"][0],
                           "value": 5.0})
        capsys.readouterr()
        with open(path) as fh:
            d = json.load(fh)
        assert d["results"][0]["value"] == 5.0
        assert "diagnostics" in d


class FakeChild:
    """Scripted stand-in for bench.Child: serves a fixed event sequence,
    then times out forever."""

    def __init__(self, events):
        self.events = list(events)
        self.killed = False

    def next_event(self, timeout):
        return self.events.pop(0) if self.events else None

    def kill(self):
        self.killed = True


class TestRunChildStateMachine:
    def _run(self, monkeypatch, events, keys=("1", "5"), cpu=False):
        children = []

        def fake_child(k, mode, c, deadline):
            child = FakeChild(events)
            children.append(child)
            return child

        monkeypatch.setattr(bench, "Child", fake_child)
        r = bench.Reporter(list(keys), {}, None, 0.0)
        status, remaining = bench.run_child(
            list(keys), "full", cpu, ready_timeout=1.0, per_config_timeout=1.0,
            reporter=r, measure_deadline=bench.time.time() + 60,
        )
        return status, remaining, r, children

    def test_no_ready_returns_all_keys(self, monkeypatch, capsys):
        status, remaining, _, children = self._run(monkeypatch, [])
        assert status == "no_ready"
        assert remaining == ["1", "5"]
        assert children[0].killed

    def test_stall_after_one_result_blames_in_flight_config(self, monkeypatch, capsys):
        events = [
            {"event": "ready", "platform": "tpu", "device_kind": "v5",
             "devices": 1, "degraded": False},
            {"event": "result", "config": "1",
             "metric": bench.CONFIG_META["1"][0], "value": 10.0},
            # then silence: config 5 is in flight when the chip dies
        ]
        status, remaining, r, _ = self._run(monkeypatch, events)
        assert status == "stalled"
        assert remaining == ["5"]  # the hung config, first in remaining
        assert r.results["1"]["value"] == 10.0

    def test_accel_child_on_cpu_routes_to_fallback(self, monkeypatch, capsys):
        events = [{"event": "ready", "platform": "cpu", "device_kind": "cpu",
                   "devices": 1, "degraded": True}]
        status, remaining, r, children = self._run(monkeypatch, events, cpu=False)
        assert status == "came_up_cpu"
        assert remaining == ["1", "5"]
        assert children[0].killed
        # the summary must NOT claim a cpu platform came up as the accelerator
        assert r.diag.get("platform") != "cpu"

    def test_clean_completion(self, monkeypatch, capsys):
        events = [
            {"event": "ready", "platform": "tpu", "device_kind": "v5",
             "devices": 1, "degraded": False},
            {"event": "result", "config": "1",
             "metric": bench.CONFIG_META["1"][0], "value": 1.0},
            {"event": "result", "config": "5",
             "metric": bench.CONFIG_META["5"][0], "value": 2.0},
            {"event": "done"},
        ]
        status, remaining, r, _ = self._run(monkeypatch, events)
        assert status == "ok" and remaining == []
        assert set(r.results) == {"1", "5"}


class TestConfigTables:
    def test_config_tables_consistent(self):
        assert set(bench.CONFIG_ORDER) == set(bench.CONFIGS) == set(bench.CONFIG_META)
        assert bench.CONFIG_ORDER[0] == bench.HEADLINE == "1"

    def test_cheap_opts_stay_cheap(self):
        # the degraded path must never pick up expensive settings by accident:
        # XLA:CPU needs 70-140 s to COMPILE a scan program and tens of
        # seconds per call (measured round 4)
        assert bench.CHEAP_OPTS["scan_cap"] <= 1
        assert bench.CHEAP_OPTS["min_measured_s"] <= 1.0
        assert bench.CHEAP_OPTS["cheap"] is True
        assert bench.FULL_OPTS["cheap"] is False

    def test_axon_boot_vars_cover_the_relay_dial(self):
        assert "PALLAS_AXON_POOL_IPS" in bench.AXON_BOOT_VARS

    def test_full_window_is_the_run_loop_steady_state(self):
        # Three manually-coupled copies of the device-loop depth: the bench
        # measures run()'s steady state, so FULL_WINDOW must track
        # ExperimentConfig.loss_fetch_every's default, and scan_cap must not
        # silently clamp it (a FULL_WINDOW raise that forgets scan_cap would
        # report device_loop_window == FULL_WINDOW while measuring less).
        import dataclasses

        from gan_deeplearning4j_tpu.harness.config import ExperimentConfig

        default = {f.name: f.default for f in dataclasses.fields(ExperimentConfig)}
        assert bench.FULL_WINDOW == default["loss_fetch_every"]
        assert bench.FULL_OPTS["scan_cap"] >= bench.FULL_WINDOW
