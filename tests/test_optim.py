"""Optimizer tests: DL4J RmsProp rule parity, LR-0 freezing, per-layer
updaters, clipping integration, and end-to-end convergence on a toy problem."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.optim import Adam, GraphOptimizer, NoOp, RmsProp, Sgd
from gan_deeplearning4j_tpu.optim.updaters import updater_from_dict


class TestRmsPropRule:
    def test_matches_dl4j_formula(self):
        """cache ← d*cache + (1-d)*g² (cache₀=eps); Δ = lr*g/sqrt(cache+eps)."""
        up = RmsProp(learning_rate=0.01, rms_decay=0.95, epsilon=1e-8)
        p = jnp.array([1.0, 2.0])
        g = jnp.array([0.5, -0.3])
        state = up.init_state(p)
        np.testing.assert_allclose(np.asarray(state["cache"]), [1e-8, 1e-8])
        delta, new_state = up.apply(state, g, p)
        cache = 1e-8 * 0.95 + np.array([0.25, 0.09]) * 0.05
        expect = 0.01 * np.array([0.5, -0.3]) / np.sqrt(cache + 1e-8)
        np.testing.assert_allclose(np.asarray(delta), expect, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(new_state["cache"]), cache, rtol=1e-6)

    def test_reference_constants_approx_sign_sgd(self):
        """With decay=eps=1e-8 (the reference's constants) the first update is
        ≈ lr·sign(g) — SURVEY §7's 'near-sign-SGD' behavior."""
        up = RmsProp(learning_rate=0.002, rms_decay=1e-8, epsilon=1e-8)
        p = jnp.zeros(3)
        g = jnp.array([10.0, -0.01, 0.5])
        delta, _ = up.apply(up.init_state(p), g, p)
        np.testing.assert_allclose(np.asarray(delta), 0.002 * np.sign(np.asarray(g)), rtol=1e-2)

    def test_lr_zero_freezes_but_state_advances(self):
        up = RmsProp(learning_rate=0.0, rms_decay=1e-8, epsilon=1e-8)
        p = jnp.ones(2)
        g = jnp.ones(2)
        state = up.init_state(p)
        delta, new_state = up.apply(state, g, p)
        np.testing.assert_array_equal(np.asarray(delta), [0.0, 0.0])
        assert not np.array_equal(np.asarray(new_state["cache"]), np.asarray(state["cache"]))


class TestOtherUpdaters:
    def test_sgd(self):
        delta, _ = Sgd(0.1).apply({}, jnp.array([1.0, -2.0]), None)
        np.testing.assert_allclose(np.asarray(delta), [0.1, -0.2])

    def test_noop(self):
        p = jnp.ones(3)
        delta, _ = NoOp().apply({}, jnp.ones(3), p)
        np.testing.assert_array_equal(np.asarray(delta), np.zeros(3))

    def test_adam_first_step(self):
        up = Adam(learning_rate=0.1)
        p = jnp.zeros(1)
        g = jnp.array([0.5])
        delta, state = up.apply(up.init_state(p), g, p)
        # bias-corrected first step ≈ lr * sign(g)
        np.testing.assert_allclose(np.asarray(delta), [0.1], rtol=1e-4)
        assert int(state["t"]) == 1

    def test_serialization_roundtrip(self):
        for up in (RmsProp(0.002, 1e-8, 1e-8), Sgd(0.1), Adam(0.001), NoOp()):
            assert updater_from_dict(up.to_dict()) == up


def two_layer_graph(l2=0.0, clip=None):
    cfg = GraphConfig(
        seed=3, l2=l2, gradient_clip=clip, gradient_clip_value=1.0, updater=Sgd(0.5)
    )
    b = GraphBuilder(cfg)
    b.add_inputs("in")
    b.set_input_types(InputType.feed_forward(2))
    b.add_layer("bn", BatchNormalization(updater=Sgd(0.5)), "in")
    b.add_layer("frozen", DenseLayer(n_out=3, updater=RmsProp(0.0, 1e-8, 1e-8)), "bn")
    b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "frozen")
    b.set_outputs("out")
    return b.build()


class TestGraphOptimizer:
    def test_freezing_and_state_params(self):
        g = two_layer_graph()
        opt = GraphOptimizer(g)
        params = g.init()
        opt_state = opt.init(params)
        # BN mean/var are state: no updater entries
        assert "mean" not in opt_state["bn"] and "gamma" in opt_state["bn"]
        # pooling-style layers without params absent entirely
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 2))
        labels = jax.nn.one_hot(jnp.array([0, 1] * 4), 2)

        def loss_fn(p):
            l, (outs, new_p) = g.loss(p, x, labels, train=True)
            return l, new_p

        grads, new_p = jax.grad(loss_fn, has_aux=True)(params)
        updated, new_state = opt.step(new_p, grads, opt_state)
        # frozen layer unchanged
        np.testing.assert_array_equal(
            np.asarray(updated["frozen"]["W"]), np.asarray(params["frozen"]["W"])
        )
        # trainable layer moved
        assert not np.array_equal(np.asarray(updated["out"]["W"]), np.asarray(params["out"]["W"]))
        # BN stats came from forward pass, not optimizer
        assert not np.array_equal(np.asarray(updated["bn"]["mean"]), np.asarray(params["bn"]["mean"]))

    def test_elementwise_clip_bounds_update(self):
        g = two_layer_graph(clip="elementwise")
        opt = GraphOptimizer(g)
        params = g.init()
        opt_state = opt.init(params)
        # fabricate a huge gradient for 'out' W with plain Sgd(0.5): update must be ≤ 0.5
        grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        grads["out"]["W"] = jnp.full_like(params["out"]["W"], 1e6)
        updated, _ = opt.step(params, grads, opt_state)
        diff = np.abs(np.asarray(updated["out"]["W"] - params["out"]["W"]))
        np.testing.assert_allclose(diff.max(), 0.5, rtol=1e-6)

    def test_jit_and_convergence(self):
        """A jitted graph-loss + optimizer step drives a small classifier to
        near-zero loss — the full train-step path works under XLA."""
        cfg = GraphConfig(seed=0, updater=RmsProp(0.01, 0.95, 1e-8))
        b = GraphBuilder(cfg)
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(2))
        b.add_layer("h", DenseLayer(n_out=16, activation="tanh"), "in")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "h")
        b.set_outputs("out")
        g = b.build()
        opt = GraphOptimizer(g)
        params = g.init()
        opt_state = opt.init(params)

        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (64, 2))
        y = (x[:, 0] > 0).astype(jnp.int32)
        labels = jax.nn.one_hot(y, 2)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                l, (outs, new_p) = g.loss(p, x, labels, train=True)
                return l, new_p

            (loss, new_p), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            new_params, new_opt = opt.step(new_p, grads, opt_state)
            return new_params, new_opt, loss

        first = None
        for i in range(150):
            params, opt_state, loss = step(params, opt_state)
            if first is None:
                first = float(loss)
        assert float(loss) < 0.1 < first
