"""DCGAN-MNIST model-family tests — the graph-level shape/param-count smoke
checks SURVEY §4 prescribes (mirroring the reference's only 'tests',
dl4jGANComputerVision.java:168-170,224-225,313-314,366-368), made exact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.models import dcgan_mnist as M
from gan_deeplearning4j_tpu.nn import ComputationGraph


@pytest.fixture(scope="module")
def graphs():
    dis = M.build_discriminator()
    gen = M.build_generator()
    gan = M.build_gan()
    return dis, gen, gan


class TestTopology:
    def test_dis_shapes(self, graphs):
        dis, _, _ = graphs
        params = dis.init()
        y = dis.output(params, jnp.ones((4, 784)))
        assert y.shape == (4, 1)

    def test_gen_shapes(self, graphs):
        _, gen, _ = graphs
        y = gen.output(gen.init(), jnp.ones((4, 2)))
        assert y.shape == (4, 28, 28, 1)  # NHWC analog of the reference's (N,1,28,28)
        # sigmoid output in [0,1]
        assert float(y.min()) >= 0.0 and float(y.max()) <= 1.0

    def test_gan_shapes(self, graphs):
        _, _, gan = graphs
        y = gan.output(gan.init(), jnp.ones((4, 2)))
        assert y.shape == (4, 1)

    def test_param_counts_match_dl4j(self, graphs):
        # counts computed from the reference topology's nIn/nOut
        dis, gen, gan = graphs
        assert dis.param_count() == 4 + 1664 + 204928 + 1180672 + 1025  # 1388293
        gen_total = 8 + 3072 + 6428800 + 25088 + 204864 + 1601  # 6663433
        assert gen.param_count() == gen_total
        assert gan.param_count() == gen_total + 1388293

    def test_layer_names_match_reference(self, graphs):
        dis, gen, gan = graphs
        assert dis.layer_names() == [
            "dis_batch_layer_1",
            "dis_conv2d_layer_2",
            "dis_maxpool_layer_3",
            "dis_conv2d_layer_4",
            "dis_maxpool_layer_5",
            "dis_dense_layer_6",
            "dis_output_layer_7",
        ]
        assert gen.layer_names()[0] == "gen_batch_1" and gen.layer_names()[-1] == "gen_conv2d_8"
        assert gan.layer_names()[-1] == "gan_dis_output_layer_15"

    def test_updater_lrs(self, graphs):
        dis, gen, gan = graphs
        dis_ups = dis.layer_updaters()
        assert all(u.learning_rate == 0.002 for u in dis_ups.values())
        gen_ups = gen.layer_updaters()
        assert all(u.learning_rate == 0.0 for u in gen_ups.values())
        gan_ups = gan.layer_updaters()
        assert gan_ups["gan_conv2d_8"].learning_rate == 0.004
        assert gan_ups["gan_dis_output_layer_15"].learning_rate == 0.0
        # all RmsProp(lr, 1e-8, 1e-8)
        assert all(u.rms_decay == 1e-8 and u.epsilon == 1e-8 for u in dis_ups.values())


class TestWeightSync:
    def test_dis_to_gan_copy_count(self, graphs):
        # 12 named params dis→gan (SURVEY §3.2)
        dis, _, gan = graphs
        n = sum(len(dis.init()[src]) for src in M.DIS_TO_GAN)
        assert n == 12

    def test_gan_to_gen_copy_count(self, graphs):
        _, _, gan = graphs
        n = sum(len(gan.init()[src]) for src in M.GAN_TO_GEN)
        assert n == 16

    def test_dis_to_cv_copy_count(self, graphs):
        dis, _, _ = graphs
        n = sum(len(dis.init()[src]) for src in M.DIS_TO_CV)
        assert n == 10

    def test_roundtrip_dis_gan_gen(self, graphs):
        """Param copy round-trip (SURVEY §4): dis→gan tail, gan gen→gen; the
        copied tensors must land under the mapped names with equal values."""
        dis, gen, gan = graphs
        dis_p = dis.init(seed=10)
        gan_p = gan.init(seed=20)
        gen_p = gen.init(seed=30)

        gan_p = ComputationGraph.copy_params(dis_p, gan_p, M.DIS_TO_GAN)
        np.testing.assert_array_equal(
            np.asarray(gan_p["gan_dis_conv2d_layer_10"]["W"]),
            np.asarray(dis_p["dis_conv2d_layer_2"]["W"]),
        )
        gen_p = ComputationGraph.copy_params(gan_p, gen_p, M.GAN_TO_GEN)
        np.testing.assert_array_equal(
            np.asarray(gen_p["gen_batch_4"]["mean"]), np.asarray(gan_p["gan_batch_4"]["mean"])
        )

    def test_gan_tail_equals_dis_after_sync(self, graphs):
        """After syncing dis→gan, the gan's discriminator tail must score a
        generated image identically to the standalone dis."""
        dis, gen, gan = graphs
        dis_p = dis.init(seed=1)
        gan_p = ComputationGraph.copy_params(dis_p, gan.init(seed=2), M.DIS_TO_GAN)

        z = jax.random.normal(jax.random.PRNGKey(0), (3, 2))
        gan_score = gan.output(gan_p, z)
        # run the gan's generator half manually via gen graph with synced params
        gen_p = ComputationGraph.copy_params(gan_p, gen.init(seed=3), M.GAN_TO_GEN)
        imgs = gen.output(gen_p, z)
        dis_score = dis.output(dis_p, imgs.reshape(3, -1))
        np.testing.assert_allclose(np.asarray(gan_score), np.asarray(dis_score), atol=1e-5)


class TestTransferClassifier:
    def test_surgery(self, graphs):
        dis, _, _ = graphs
        dis_p = dis.init(seed=5)
        cv, cv_p = M.build_transfer_classifier(dis, dis_p)
        # feature layers carried over
        np.testing.assert_array_equal(
            np.asarray(cv_p["dis_conv2d_layer_2"]["W"]), np.asarray(dis_p["dis_conv2d_layer_2"]["W"])
        )
        # head replaced: 10-way softmax
        y = cv.output(cv_p, jnp.ones((4, 784)))
        assert y.shape == (4, 10)
        np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), np.ones(4), atol=1e-5)

    def test_freeze_semantics(self, graphs):
        dis, _, _ = graphs
        cv, _ = M.build_transfer_classifier(dis, dis.init())
        ups = cv.layer_updaters()
        # frozen up to and including dis_dense_layer_6
        for name in ("dis_batch_layer_1", "dis_conv2d_layer_2", "dis_conv2d_layer_4", "dis_dense_layer_6"):
            assert ups[name].learning_rate == 0.0, name
        # new head trains at 0.002
        assert ups["dis_batch"].learning_rate == 0.002
        assert ups["dis_output_layer_7"].learning_rate == 0.002

    def test_param_count(self, graphs):
        dis, _, _ = graphs
        cv, cv_p = M.build_transfer_classifier(dis, dis.init())
        expected = (4 + 1664 + 204928 + 1180672) + 4096 + 10250
        assert sum(int(p.size) for lp in cv_p.values() for p in lp.values()) == expected


class TestGanGradientFlow:
    @pytest.mark.slow
    def test_generator_gets_gradients_through_frozen_dis(self, graphs):
        """One XENT loss at the stacked head; generator layers must receive
        nonzero grads through the frozen tail (the whole point of the gan
        graph, dl4jGANComputerVision.java:227-314)."""
        _, _, gan = graphs
        params = gan.init()
        z = jax.random.uniform(jax.random.PRNGKey(1), (8, 2), minval=-1, maxval=1)
        ones = jnp.ones((8, 1))

        def loss_fn(p):
            l, _ = gan.loss(p, z, ones, train=True)
            return l

        grads = jax.grad(loss_fn)(params)
        g_gen = float(jnp.sum(jnp.abs(grads["gan_conv2d_6"]["W"])))
        g_dis = float(jnp.sum(jnp.abs(grads["gan_dis_conv2d_layer_10"]["W"])))
        assert g_gen > 0.0
        assert g_dis > 0.0  # grads exist; freezing happens in the updater (LR 0)
