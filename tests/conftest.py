"""Test configuration: run everything on a fake 8-device CPU mesh.

The reference's trick for exercising the distributed path without a cluster is
Spark ``local[4]`` (dl4jGANComputerVision.java:318). Ours is XLA's host
platform with 8 virtual devices, so data-parallel/all-reduce paths run in CI
without TPUs (SURVEY §4 item 4). Must be set before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Opt the suite back into the persistent compilation cache: CPU persistence
# is off by default (cpu_aot_loader noise / cross-host SIGILL risk in
# driver-facing tails — runtime/environment.py), but for tests the warm
# cache saves minutes and the load warnings only reach pytest's captured
# output. The per-host tag inside keeps entries host-compatible.
os.environ.setdefault(
    "GDT_COMPILATION_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)

import jax  # noqa: E402

# The image's sitecustomize registers the TPU PJRT plugin and pins
# jax_platforms via jax.config, which wins over the env var — pin it back.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import jax

    return jax.random.PRNGKey(666)


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Fresh process-wide metrics registry per test (and a quiet tracer).

    The telemetry registry is process-wide BY DESIGN (one serving process =
    one registry); a pytest process runs hundreds of "processes" worth of
    engines and batchers back to back, so without this swap every test
    would read the previous tests' series. Swapping the default registry
    gives each test the single-process view production sees. The span
    tracer is a disabled-by-default singleton; tests that enable it get it
    disabled and drained again afterwards."""
    from gan_deeplearning4j_tpu.telemetry.registry import (
        MetricsRegistry,
        set_registry,
    )
    from gan_deeplearning4j_tpu.telemetry.trace import TRACER

    previous = set_registry(MetricsRegistry())
    try:
        yield
    finally:
        set_registry(previous)
        TRACER.disable()
        TRACER.clear()
