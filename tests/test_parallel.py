"""Distributed-trainer tests on the fake 8-device CPU mesh (SURVEY §4 item 4
— the analog of the reference's Spark ``local[4]`` trick).

Covers: single-chip convergence, mesh-vs-single-chip numerical equivalence
(per-step gradient sync), and parameter-averaging semantics — the shard_map
round must equal W independent local fits followed by an arithmetic mean
(the map-reduce of gan.ipynb cell 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import ArrayDataSetIterator
from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.optim import GraphOptimizer, RmsProp
from gan_deeplearning4j_tpu.parallel import (
    GraphTrainer,
    ParameterAveragingTrainer,
    TrainState,
)
from gan_deeplearning4j_tpu.runtime import TpuEnvironment


def small_classifier(n_in=8, n_hidden=16, n_classes=3, lr=0.01):
    b = GraphBuilder(
        GraphConfig(
            seed=666,
            l2=1e-4,
            gradient_clip="elementwise",
            gradient_clip_value=1.0,
            updater=RmsProp(lr, 0.95, 1e-8),
        )
    )
    b.add_inputs("in")
    b.set_input_types(InputType.feed_forward(n_in))
    b.add_layer("dense", DenseLayer(n_out=n_hidden), "in")
    b.add_layer("bn", BatchNormalization(), "dense")
    b.add_layer("out", OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"), "bn")
    b.set_outputs("out")
    return b.build()


def toy_data(n=256, n_in=8, n_classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = (np.abs(x).sum(axis=1) * 1.7).astype(np.int64) % n_classes
    onehot = np.zeros((n, n_classes), np.float32)
    onehot[np.arange(n), y] = 1.0
    return x, onehot


class TestGraphTrainer:
    def test_loss_decreases_single_chip(self):
        graph = small_classifier()
        trainer = GraphTrainer(graph)
        state = trainer.init_state()
        x, y = toy_data()
        it = ArrayDataSetIterator(x, y, batch_size=32)
        state, losses = trainer.fit(state, it)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert int(state.step) == len(losses)

    def test_bn_stats_update_through_step(self):
        graph = small_classifier()
        trainer = GraphTrainer(graph)
        state = trainer.init_state()
        x, y = toy_data(64)
        before = np.asarray(state.params["bn"]["mean"])
        state, _ = trainer.train_step(state, jnp.asarray(x), jnp.asarray(y))
        after = np.asarray(state.params["bn"]["mean"])
        assert not np.allclose(before, after)

    def test_mesh_step_matches_single_chip(self):
        """Per-step gradient sync on the mesh is the same global-batch math as
        one chip: params replicated, batch sharded, XLA inserts the
        all-reduce. Results must agree to float tolerance."""
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        solo = GraphTrainer(graph, donate=False)
        dist = GraphTrainer(graph, mesh=mesh, donate=False)
        x, y = toy_data(128)
        xs, ys = jnp.asarray(x), jnp.asarray(y)
        s_solo = solo.init_state()
        s_dist = dist.init_state()
        for _ in range(3):
            s_solo, l_solo = solo.train_step(s_solo, xs, ys)
            s_dist, l_dist = dist.train_step(s_dist, xs, ys)
        np.testing.assert_allclose(float(l_solo), float(l_dist), rtol=1e-4)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            s_solo.params,
            s_dist.params,
        )

    def test_output_on_mesh(self):
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        trainer = GraphTrainer(graph, mesh=mesh)
        state = trainer.init_state()
        x, _ = toy_data(64)
        out = trainer.output(state, jnp.asarray(x))
        assert out.shape == (64, 3)
        np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-5)


class TestParameterAveraging:
    def test_round_equals_manual_worker_average(self):
        """One shard_map round == W independent local fits + arithmetic mean
        of params and updater state (ParameterAveragingTrainingMaster
        semantics, dl4jGANComputerVision.java:325-330)."""
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        W, freq, b = 8, 2, 4
        pa = ParameterAveragingTrainer(
            graph, mesh, batch_size_per_worker=b, averaging_frequency=freq
        )
        assert pa.num_workers == W
        x, y = toy_data(W * freq * b)
        state0 = pa.init_state()
        state1, losses = pa.fit_round(state0, jnp.asarray(x), jnp.asarray(y))
        assert losses.shape == (freq,)
        assert np.isfinite(np.asarray(losses)).all()
        assert int(state1.step) == freq

        # manual reproduction with the single-chip machinery
        opt = GraphOptimizer(graph)
        params0 = graph.init()
        opt0 = opt.init(params0)
        worker_params, worker_opt = [], []
        for w in range(W):
            p, s = params0, opt0
            for k in range(freq):
                lo = w * freq * b + k * b
                mb_x, mb_y = jnp.asarray(x[lo : lo + b]), jnp.asarray(y[lo : lo + b])

                def loss_fn(pp):
                    loss, (_, new_p) = graph.loss(pp, mb_x, mb_y, train=True)
                    return loss, new_p

                (_, new_p), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
                p, s = opt.step(new_p, grads, s)
            worker_params.append(p)
            worker_opt.append(s)
        mean_params = jax.tree_util.tree_map(
            lambda *xs: sum(xs) / len(xs), *worker_params
        )
        mean_opt = jax.tree_util.tree_map(lambda *xs: sum(xs) / len(xs), *worker_opt)
        jax.tree_util.tree_map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5
            ),
            state1.params,
            mean_params,
        )
        jax.tree_util.tree_map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5
            ),
            state1.opt_state,
            mean_opt,
        )

    def test_averaging_differs_from_per_step_sync(self):
        """freq>1 local divergence is a different algorithm from per-step
        gradient averaging (SURVEY §7 hard parts) — assert they disagree."""
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        W, freq, b = 8, 4, 4
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=b, averaging_frequency=freq)
        x, y = toy_data(W * freq * b)
        s_pa, _ = pa.fit_round(pa.init_state(), jnp.asarray(x), jnp.asarray(y))
        sync = GraphTrainer(graph, mesh=mesh, donate=False)
        s_sync = sync.init_state()
        # same data as freq global steps of W*b rows (worker-major regroup)
        xr = np.asarray(x).reshape(W, freq, b, -1).swapaxes(0, 1).reshape(freq, W * b, -1)
        yr = np.asarray(y).reshape(W, freq, b, -1).swapaxes(0, 1).reshape(freq, W * b, -1)
        for k in range(freq):
            s_sync, _ = sync.train_step(s_sync, jnp.asarray(xr[k]), jnp.asarray(yr[k]))
        diffs = jax.tree_util.tree_map(
            lambda a, b_: float(np.max(np.abs(np.asarray(a) - np.asarray(b_)))),
            s_pa.params,
            s_sync.params,
        )
        assert max(jax.tree_util.tree_leaves(diffs)) > 1e-6

    def test_iterator_front_end_honors_frequency(self):
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=4, averaging_frequency=2)
        # 168 rows: two full rounds of 8*2*4=64, then a tail round of freq 1
        # (32 rows), then a ragged-tail round for the last 8 rows (1/worker) —
        # every example trains, nothing is dropped
        x, y = toy_data(8 * 2 * 4 * 2 + 40)
        it = ArrayDataSetIterator(x, y, batch_size=32)
        state, losses = pa.fit(pa.init_state(), it)
        assert len(losses) == 2 + 2 + 1 + 1
        assert int(state.step) == 6
        assert np.isfinite(losses).all()

    def test_small_fit_still_trains(self):
        # fewer rows than workers*batch must still produce an update (the
        # GanExperiment per-iteration fits are exactly this shape)
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=200, averaging_frequency=10)
        x, y = toy_data(24)
        it = ArrayDataSetIterator(x, y, batch_size=24)
        state0 = pa.init_state()
        p0 = jax.tree_util.tree_map(np.asarray, state0.params)
        state, losses = pa.fit(state0, it)
        assert len(losses) == 1 and np.isfinite(losses).all()
        assert int(state.step) == 1
        diffs = jax.tree_util.tree_map(
            lambda a, b_: float(np.max(np.abs(np.asarray(a) - b_))), state.params, p0
        )
        assert max(jax.tree_util.tree_leaves(diffs)) > 0

    def test_bad_round_size_raises(self):
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=4, averaging_frequency=2)
        x, y = toy_data(17)
        with pytest.raises(ValueError):
            pa.fit_round(pa.init_state(), jnp.asarray(x), jnp.asarray(y))

    def test_fit_rounds_matches_sequential_rounds(self):
        """K scanned averaging rounds in one dispatch == K sequential
        fit_round calls chained through the same split(rng) sequence (the
        round-4 device loop for the faithful mode, VERDICT r3 item 5)."""
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        W, freq, b, K = 8, 2, 4, 3
        pa = ParameterAveragingTrainer(
            graph, mesh, batch_size_per_worker=b, averaging_frequency=freq
        )
        x, y = toy_data(K * W * freq * b, seed=5)
        xs = jnp.asarray(x.reshape(K, W * freq * b, -1))
        ys = jnp.asarray(y.reshape(K, W * freq * b, -1))
        rng = jax.random.PRNGKey(123)

        s_scan, losses_scan = pa.fit_rounds(pa.init_state(), xs, ys, rng=rng)
        assert losses_scan.shape == (K, freq)
        assert int(s_scan.step) == K * freq

        s_seq = pa.init_state()
        r = rng
        seq_losses = []
        for i in range(K):
            r, sub = jax.random.split(r)
            s_seq, l = pa.fit_round(s_seq, xs[i], ys[i], rng=sub)
            seq_losses.append(np.asarray(l))
        np.testing.assert_allclose(
            np.asarray(losses_scan), np.stack(seq_losses), rtol=2e-5, atol=1e-6
        )
        jax.tree_util.tree_map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5
            ),
            s_scan.params,
            s_seq.params,
        )
        jax.tree_util.tree_map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5
            ),
            s_scan.opt_state,
            s_seq.opt_state,
        )

    def test_fit_drains_buffered_rounds_in_one_dispatch(self):
        """fit() with several FULL rounds buffered routes them through
        fit_rounds (one scanned dispatch) and must match the per-round
        sequential drain bit-for-bit — the rng chain is aligned by
        construction."""
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=4,
                                       averaging_frequency=2)
        rows = pa.round_examples
        x, y = toy_data(2 * rows, seed=9)
        # whole buffer arrives at once -> k=2 scanned drain
        s_scan, l_scan = pa.fit(
            pa.init_state(), ArrayDataSetIterator(x, y, batch_size=2 * rows)
        )
        # one round per batch -> k=1 sequential drains
        s_seq, l_seq = pa.fit(
            pa.init_state(), ArrayDataSetIterator(x, y, batch_size=rows)
        )
        assert len(l_scan) == len(l_seq) == 4  # 2 rounds x freq 2
        np.testing.assert_allclose(l_scan, l_seq, rtol=2e-5, atol=1e-6)
        assert int(s_scan.step) == int(s_seq.step) == 4
        jax.tree_util.tree_map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5
            ),
            s_scan.params,
            s_seq.params,
        )

    def test_fit_rounds_bad_shape_raises(self):
        graph = small_classifier()
        mesh = TpuEnvironment().make_mesh()
        pa = ParameterAveragingTrainer(graph, mesh, batch_size_per_worker=4, averaging_frequency=2)
        x, y = toy_data(2 * 60)
        with pytest.raises(ValueError):
            pa.fit_rounds(
                pa.init_state(),
                jnp.asarray(x.reshape(2, 60, -1)),
                jnp.asarray(y.reshape(2, 60, -1)),
            )
