"""Data layer tests (SURVEY §2.2 D13-D14): CSV round trip, iterator semantics
(batching, one-hot labelization, reset — dl4jGANComputerVision.java:372-377,
395-400,600-602), prefetch wrapper, synthetic MNIST contract."""

import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import (
    ArrayDataSetIterator,
    ClassPathResource,
    CSVRecordReader,
    DataSet,
    DevicePrefetchIterator,
    FileSplit,
    InMemoryRecordReader,
    RecordReaderDataSetIterator,
    load_mnist_csv,
    synthetic_mnist,
    write_mnist_csv,
)
from gan_deeplearning4j_tpu.data.mnist import prepare_mnist, stratified_sample


def test_synthetic_mnist_contract():
    (xtr, ytr), (xte, yte) = synthetic_mnist(num_train=300, num_test=60)
    assert xtr.shape == (300, 784) and xte.shape == (60, 784)
    assert xtr.dtype == np.float32
    assert xtr.min() >= 0.0 and xtr.max() <= 1.0
    assert set(np.unique(ytr)) <= set(range(10))
    # deterministic across calls
    (xtr2, ytr2), _ = synthetic_mnist(num_train=300, num_test=60)
    np.testing.assert_array_equal(xtr, xtr2)
    np.testing.assert_array_equal(ytr, ytr2)


def test_synthetic_mnist_classes_are_separable():
    # class templates must be distinct enough that nearest-template
    # classification beats chance by a wide margin — real learnable signal
    (xtr, ytr), _ = synthetic_mnist(num_train=500, num_test=10)
    means = np.stack([xtr[ytr == c].mean(axis=0) for c in range(10)])
    d = ((xtr[:, None, :] - means[None, :, :]) ** 2).sum(-1)
    acc = (d.argmin(axis=1) == ytr).mean()
    assert acc > 0.9


def test_csv_round_trip(tmp_path):
    (x, y), _ = synthetic_mnist(num_train=50, num_test=10)
    path = write_mnist_csv(str(tmp_path / "mnist_train.csv"), x, y)
    x2, y2 = load_mnist_csv(path)
    assert x2.shape == (50, 784)
    np.testing.assert_array_equal(y, y2)
    # %.2f quantization: within half a cent
    assert np.abs(x - x2).max() <= 0.005 + 1e-6


def test_classpath_resource_and_filesplit(tmp_path, monkeypatch):
    p = tmp_path / "res.csv"
    np.savetxt(p, np.eye(3), delimiter=",", fmt="%.2f")
    monkeypatch.setenv("GAN_DL4J_TPU_DATA", str(tmp_path))
    resource = ClassPathResource("res.csv")
    assert resource.get_file() == str(p)
    reader = CSVRecordReader(0, ",")
    reader.initialize(FileSplit(resource))
    assert reader.data.shape == (3, 3)
    with pytest.raises(FileNotFoundError):
        ClassPathResource("missing.csv", roots=[str(tmp_path)]).get_file()


def test_record_reader_dataset_iterator(tmp_path):
    (x, y), _ = synthetic_mnist(num_train=25, num_test=5)
    path = write_mnist_csv(str(tmp_path / "t.csv"), x, y)
    reader = CSVRecordReader(0, ",")
    reader.initialize(FileSplit(path))
    it = RecordReaderDataSetIterator(reader, batch_size=10, label_index=784, num_classes=10)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [10, 10, 5]
    b0 = batches[0]
    assert b0.features.shape == (10, 784)
    assert b0.labels.shape == (10, 10)
    np.testing.assert_allclose(np.asarray(b0.labels).sum(axis=1), 1.0)
    np.testing.assert_array_equal(np.asarray(b0.labels).argmax(axis=1), y[:10])
    # reset restarts from the top (dl4jGANComputerVision.java:600-602)
    assert not it.has_next()
    it.reset()
    again = it.next()
    np.testing.assert_array_equal(np.asarray(again.features), np.asarray(b0.features))


def test_in_memory_reader_unlabeled():
    data = np.arange(12, dtype=np.float32).reshape(4, 3)
    it = RecordReaderDataSetIterator(InMemoryRecordReader(data), batch_size=3)
    b = it.next()
    assert b.labels is None
    assert b.features.shape == (3, 3)


def test_array_iterator_shuffle_and_epochs():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.eye(10, dtype=np.float32)
    it = ArrayDataSetIterator(x, y, batch_size=4, shuffle=True, seed=7)
    epoch1 = np.concatenate([np.asarray(b.features) for b in it])
    epoch2 = np.concatenate([np.asarray(b.features) for b in it])
    # same multiset of rows, different order per epoch
    assert sorted(epoch1.ravel().tolist()) == sorted(x.ravel().tolist())
    assert not np.array_equal(epoch1, epoch2)


def test_dataset_merge_and_pytree():
    import jax

    a = DataSet(np.ones((2, 3), np.float32), np.zeros((2, 1), np.float32))
    b = DataSet(np.zeros((3, 3), np.float32), np.ones((3, 1), np.float32))
    m = DataSet.merge([a, b])
    assert m.num_examples() == 5
    leaves = jax.tree_util.tree_leaves(m)
    assert len(leaves) == 2
    doubled = jax.tree_util.tree_map(lambda v: v * 2, m)
    assert isinstance(doubled, DataSet)


def test_device_prefetch_matches_inner():
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    inner = ArrayDataSetIterator(x, batch_size=5)
    pre = DevicePrefetchIterator(ArrayDataSetIterator(x, batch_size=5), depth=3)
    got = [np.asarray(b.features) for b in pre]
    want = [np.asarray(b.features) for b in inner]
    assert len(got) == len(want) == 3
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
    pre.reset()
    assert pre.has_next()


def test_device_prefetch_transform_hook():
    # the host-side per-batch hook (jaxlint JG019's seam): applied before
    # device placement, once per batch
    from gan_deeplearning4j_tpu.data.dataset import DataSet

    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    seen = []

    def scale(batch):
        seen.append(batch.num_examples())
        return DataSet(batch.features * 2.0)

    pre = DevicePrefetchIterator(
        ArrayDataSetIterator(x, batch_size=6), depth=2, transform=scale)
    got = np.concatenate([np.asarray(b.features) for b in pre])
    np.testing.assert_array_equal(got, x * 2.0)
    assert seen == [6, 6]


def test_stratified_sample_and_prepare(tmp_path):
    (x, y), _ = synthetic_mnist(num_train=400, num_test=50)
    xs, ys = stratified_sample(x, y, per_class=5)
    counts = np.bincount(ys, minlength=10)
    assert (counts <= 5).all() and counts.sum() == len(ys)
    train_p, test_p = prepare_mnist(str(tmp_path), num_train=60, num_test=20)
    xt, yt = load_mnist_csv(train_p)
    assert xt.shape == (60, 784) and yt.shape == (60,)
    assert (tmp_path / "sampled_mnist_train.csv").exists()


class TestIdxAndRealDigits:
    """Round-2 VERDICT item 7: IDX support + real-data-over-synthetic."""

    def _write_idx(self, path, arr):
        import struct

        codes = {np.dtype(np.uint8): 0x08, np.dtype(">i4"): 0x0C}
        with open(path, "wb") as fh:
            fh.write(bytes([0, 0, codes[arr.dtype], arr.ndim]))
            for d in arr.shape:
                fh.write(struct.pack(">i", d))
            fh.write(arr.tobytes())

    def test_idx_roundtrip(self, tmp_path):
        from gan_deeplearning4j_tpu.data.mnist import read_idx

        arr = np.arange(2 * 5 * 4, dtype=np.uint8).reshape(2, 5, 4)
        p = str(tmp_path / "x-idx3-ubyte")
        self._write_idx(p, arr)
        np.testing.assert_array_equal(read_idx(p), arr)

    def test_idx_gzip_and_errors(self, tmp_path):
        import gzip

        from gan_deeplearning4j_tpu.data.mnist import read_idx

        arr = np.arange(12, dtype=np.uint8).reshape(3, 4)
        raw_path = str(tmp_path / "y-idx2-ubyte")
        self._write_idx(raw_path, arr)
        gz_path = raw_path + ".gz"
        with open(raw_path, "rb") as src, gzip.open(gz_path, "wb") as dst:
            dst.write(src.read())
        np.testing.assert_array_equal(read_idx(gz_path), arr)
        bad = str(tmp_path / "bad")
        with open(bad, "wb") as fh:
            fh.write(b"\x01\x02\x03\x04")
        with pytest.raises(ValueError):
            read_idx(bad)

    def test_load_mnist_idx_directory(self, tmp_path):
        from gan_deeplearning4j_tpu.data.mnist import load_mnist_idx

        rng = np.random.default_rng(0)
        tr_img = rng.integers(0, 256, size=(6, 28, 28)).astype(np.uint8)
        te_img = rng.integers(0, 256, size=(3, 28, 28)).astype(np.uint8)
        tr_lab = (np.arange(6) % 10).astype(np.uint8)
        te_lab = (np.arange(3) % 10).astype(np.uint8)
        names = {
            "train-images-idx3-ubyte": tr_img,
            "train-labels-idx1-ubyte": tr_lab,
            "t10k-images-idx3-ubyte": te_img,
            "t10k-labels-idx1-ubyte": te_lab,
        }
        for name, arr in names.items():
            self._write_idx(str(tmp_path / name), arr)
        (xtr, ytr), (xte, yte) = load_mnist_idx(str(tmp_path))
        assert xtr.shape == (6, 784) and xte.shape == (3, 784)
        assert xtr.dtype == np.float32 and 0.0 <= xtr.min() and xtr.max() <= 1.0
        np.testing.assert_array_equal(ytr, tr_lab)

    def test_find_mnist_idx_env(self, tmp_path, monkeypatch):
        from gan_deeplearning4j_tpu.data.mnist import find_mnist_idx

        monkeypatch.setenv("MNIST_DIR", str(tmp_path))
        assert find_mnist_idx() is None  # incomplete dir is not a hit
        rng = np.random.default_rng(0)
        for name, shape, code in (
            ("train-images-idx3-ubyte", (2, 28, 28), None),
            ("train-labels-idx1-ubyte", (2,), None),
            ("t10k-images-idx3-ubyte", (2, 28, 28), None),
            ("t10k-labels-idx1-ubyte", (2,), None),
        ):
            self._write_idx(
                str(tmp_path / name),
                rng.integers(0, 10, size=shape).astype(np.uint8),
            )
        assert find_mnist_idx() == str(tmp_path)

    def test_real_digits_shapes(self):
        from gan_deeplearning4j_tpu.data.mnist import real_digits

        (xtr, ytr), (xte, yte) = real_digits(num_train=2500, num_test=100)
        assert xtr.shape == (2500, 784) and xte.shape == (100, 784)
        assert xtr.dtype == np.float32
        assert 0.0 <= xtr.min() and xtr.max() <= 1.0
        assert set(np.unique(ytr)) <= set(range(10))
        # real data: every class present at this sample size
        assert len(np.unique(ytr)) == 10

    def test_load_mnist_prefers_real(self):
        from gan_deeplearning4j_tpu.data.mnist import load_mnist

        tag, ((xtr, ytr), _) = load_mnist(num_train=50, num_test=10)
        # this image has sklearn but no IDX MNIST → the real UCI digits win
        assert tag == "uci-digits-upsampled"
        assert xtr.shape == (50, 784)
