"""serving/ladder tests: the exact bucket-ladder DP and its waste
oracle, the bounded flush-size histogram, manifest persistence
round-trips, the batcher's flush-seam recording, the mux registry's
per-variant ladders + adoption carry-forward, the reload plane's
learned-ladder resolution order, and the fleet manager's
compilation-cache propagation (ISSUE 19; docs/SERVING.md).

Everything here is jax-free: the DP/histogram are pure python, the
batcher runs in ``run_fn`` mode, and the registry/reloader use
engine-shaped fakes — millisecond tests for the learning loop's
invariants."""

import types

import numpy as np
import pytest

from gan_deeplearning4j_tpu.deploy.reloader import (
    ReloadController,
    _ladder_priority,
)
from gan_deeplearning4j_tpu.fleet.manager import FleetManager
from gan_deeplearning4j_tpu.fleet.router import FleetRouter
from gan_deeplearning4j_tpu.serving.batcher import MicroBatcher
from gan_deeplearning4j_tpu.serving.ladder import (
    SizeHistogram,
    expected_waste,
    manifest_histogram,
    manifest_ladder,
    solve_ladder,
    write_ladder_block,
)
from gan_deeplearning4j_tpu.serving.mux import MuxRegistry
from gan_deeplearning4j_tpu.quant.variants import (
    read_bundle_manifest,
    write_bundle_manifest,
)


# ===========================================================================
# solve_ladder — the exact DP
# ===========================================================================

class TestSolveLadder:
    def test_empty_histogram_returns_top(self):
        assert solve_ladder({}, budget=4, top=128) == (128,)

    def test_budget_one_degenerates_to_top(self):
        assert solve_ladder({3: 50, 7: 9}, budget=1, top=128) == (128,)

    def test_budget_below_one_raises(self):
        with pytest.raises(ValueError):
            solve_ladder({3: 1}, budget=0, top=128)

    def test_empty_histogram_without_top_raises(self):
        with pytest.raises(ValueError):
            solve_ladder({}, budget=4)

    def test_free_budget_places_bucket_at_every_remainder(self):
        # 128 % 128 == 0 drops out; three remainders, three free slots
        counts = {3: 50, 4: 20, 100: 7, 128: 3}
        ladder = solve_ladder(counts, budget=4, top=128)
        assert ladder == (3, 4, 100, 128)
        assert expected_waste(counts, ladder) == 0

    def test_constrained_budget_picks_the_cheapest_cut(self):
        # one free bucket among {4, 5, 64}: at 5 the hundred 4-row
        # flushes pad 1 row each (100) and the 64s fall to top (640);
        # at 4 the single 5 and the 64s fall to top (123 + 640); at 64
        # the 4s pad 60 rows each. 740 < 763 < 6059.
        counts = {4: 100, 5: 1, 64: 10}
        ladder = solve_ladder(counts, budget=2, top=128)
        assert ladder == (5, 128)
        assert expected_waste(counts, ladder) == 740
        assert expected_waste(counts, (4, 128)) == 763

    def test_solution_matches_brute_force(self):
        import itertools

        counts = {2: 30, 3: 11, 9: 8, 17: 40, 31: 5, 60: 2}
        top, budget = 64, 3
        best = min(
            (expected_waste(counts, combo + (top,))
             for k in range(1, budget)
             for combo in itertools.combinations(sorted(counts), k)),
            default=expected_waste(counts, (top,)))
        ladder = solve_ladder(counts, budget=budget, top=top)
        assert expected_waste(counts, ladder) == best

    def test_deterministic_under_dict_order(self):
        counts = {4: 100, 5: 1, 64: 10, 17: 3}
        reversed_counts = dict(reversed(list(counts.items())))
        assert (solve_ladder(counts, budget=3, top=128)
                == solve_ladder(reversed_counts, budget=3, top=128))

    def test_top_always_present_even_when_never_observed(self):
        ladder = solve_ladder({3: 10}, budget=4, top=99)
        assert ladder[-1] == 99 and ladder == (3, 99)

    def test_sizes_above_top_fold_to_remainders(self):
        # 130 % 128 == 2: the chunker serves a full waste-free 128-chunk
        # plus a 2-row remainder — the DP must plan for the 2, not 130
        assert solve_ladder({130: 5}, budget=2, top=128) == (2, 128)
        # exact multiples of top are entirely waste-free: nothing to learn
        assert solve_ladder({256: 9}, budget=4, top=128) == (128,)

    def test_string_keys_accepted(self):
        # JSON round-trips histogram keys as strings
        assert solve_ladder({"3": "7"}, budget=2, top=16) == (3, 16)

    def test_top_defaults_to_largest_observed(self):
        assert solve_ladder({3: 5, 40: 1}, budget=2, top=None)[-1] == 40


class TestExpectedWaste:
    def test_hand_computed(self):
        # 2 flushes of 3 pad to 4 (waste 2), one of 10 pads to 16 (6)
        assert expected_waste({3: 2, 10: 1}, (4, 16)) == 8

    def test_full_chunks_are_waste_free(self):
        assert expected_waste({16: 5, 32: 2}, (4, 16)) == 0
        # 20 = one 16-chunk + remainder 4 → pads to bucket 4, waste 0
        assert expected_waste({20: 3}, (4, 16)) == 0

    def test_bad_ladder_raises(self):
        with pytest.raises(ValueError):
            expected_waste({3: 1}, ())
        with pytest.raises(ValueError):
            expected_waste({3: 1}, (0, 8))


# ===========================================================================
# SizeHistogram — bounded, thread-safe, JSON-tolerant
# ===========================================================================

class TestSizeHistogram:
    def test_record_snapshot_total(self):
        h = SizeHistogram()
        h.record("sample", 3)
        h.record("sample", 3)
        h.record("discriminate", 7)
        assert h.snapshot() == {"sample": {3: 2}, "discriminate": {7: 1}}
        assert h.total() == 3

    def test_nonpositive_records_ignored(self):
        h = SizeHistogram()
        h.record("sample", 0)
        h.record("sample", -4)
        assert h.total() == 0

    def test_merged_pools_across_kinds(self):
        h = SizeHistogram()
        h.record("a", 3)
        h.record("b", 3)
        h.record("b", 9)
        assert h.merged() == {3: 2, 9: 1}

    def test_merge_accepts_json_string_keys(self):
        h = SizeHistogram()
        h.merge({"sample": {"4": "6", "junk": 1, "2": 0}})
        assert h.snapshot() == {"sample": {4: 6}}

    def test_overflow_folds_up_then_to_largest(self):
        h = SizeHistogram(max_sizes=2)
        h.record("k", 5)
        h.record("k", 10)
        h.record("k", 7)   # unseen, folds UP to 10 (conservative)
        h.record("k", 20)  # above everything: folds into largest (10)
        assert h.snapshot() == {"k": {5: 1, 10: 3}}
        assert h.stats()["folded"] == 2

    def test_max_sizes_below_one_raises(self):
        with pytest.raises(ValueError):
            SizeHistogram(max_sizes=0)

    def test_stats_block_shape(self):
        h = SizeHistogram()
        h.record("sample", 4)
        s = h.stats()
        assert s["total"] == 1 and s["folded"] == 0
        assert s["kinds"] == {"sample": {"4": 1}}  # str keys: JSON-ready


# ===========================================================================
# manifest persistence — the ladder travels with the bundle
# ===========================================================================

class TestManifestRoundTrip:
    def seed(self, tmp_path):
        d = str(tmp_path)
        write_bundle_manifest(d, {"generation": 7})
        return d

    def test_write_then_read_back(self, tmp_path):
        d = self.seed(tmp_path)
        write_ladder_block(d, [8, 1, 8, 32],
                           histogram={"sample": {3: 50, 130: 2}},
                           solved_from={"total_rows": 52})
        assert manifest_ladder(d) == (1, 8, 32)  # sorted, deduped
        assert manifest_histogram(d) == {"sample": {3: 50, 130: 2}}
        # rides NEXT TO existing manifest keys, never replaces them
        assert read_bundle_manifest(d)["generation"] == 7

    def test_bad_ladder_rejected_at_write(self, tmp_path):
        d = self.seed(tmp_path)
        with pytest.raises(ValueError):
            write_ladder_block(d, [])
        with pytest.raises(ValueError):
            write_ladder_block(d, [0, 8])

    def test_absent_and_malformed_blocks_read_as_none(self, tmp_path):
        d = self.seed(tmp_path)
        assert manifest_ladder(d) is None
        assert manifest_histogram(d) is None
        # malformed blocks must degrade to defaults, never fail a load
        write_bundle_manifest(d, {"ladder": {"buckets": ["x"],
                                             "histogram": "nope"}})
        assert manifest_ladder(d) is None
        assert manifest_histogram(d) is None
        assert manifest_ladder(str(tmp_path / "missing")) is None


# ===========================================================================
# batcher — the flush seam is the only recording site
# ===========================================================================

class TestBatcherFlushRecording:
    def test_flush_sizes_recorded_and_exported(self):
        mb = MicroBatcher(lambda kind, rows: rows * 2.0,
                          max_batch=16, max_latency=0.0,
                          default_timeout=5.0)
        try:
            for n in (3, 3, 7):
                r = mb.submit("sample", np.zeros((n, 2), np.float32))
                assert r.ok
            # sequential submits → each flush is exactly one request
            assert mb.size_histogram.snapshot() == {"sample": {3: 2, 7: 1}}
            assert mb.metrics()["flush_sizes"]["total"] == 3
        finally:
            mb.close()

    def test_injected_histogram_is_shared(self):
        # the mux hands each variant's batcher the VARIANT's histogram;
        # the seam is the constructor kwarg
        h = SizeHistogram()
        mb = MicroBatcher(lambda kind, rows: rows, max_batch=8,
                          max_latency=0.0, default_timeout=5.0,
                          size_histogram=h)
        try:
            assert mb.submit("sample", np.ones((4, 2), np.float32)).ok
            assert h.merged() == {4: 1}
            assert mb.size_histogram is h
        finally:
            mb.close()


# ===========================================================================
# mux registry — per-variant ladders + adoption carry-forward
# ===========================================================================

class _LadderFake:
    """Engine-shaped fake carrying its own learned ladder."""

    def __init__(self, name, buckets=None, generation=None):
        self.name = name
        self.generation = generation
        self.warmed = True
        self.warm_failed = False
        self.kinds = ("sample",)
        if buckets is not None:
            self.buckets = tuple(buckets)

    def warmup(self, background=False):
        return {}

    def input_width(self, kind):
        return 2

    def dispatch(self, kind, rows_list):
        return types.SimpleNamespace(
            lane=0, rows=[np.asarray(r) for r in rows_list])

    def finalize(self, flight):
        return np.concatenate(flight.rows)


def _registry(**kw):
    kw.setdefault("batcher_kwargs",
                  {"max_latency": 0.0, "default_timeout": 2.0})
    return MuxRegistry(buckets=(1, 8), budget=4,
                       build=lambda variant: _LadderFake(variant.name),
                       **kw)


class TestMuxPerVariantLadder:
    def test_batcher_tops_out_at_the_engines_own_ladder(self):
        reg = _registry()
        try:
            reg.add("wide", engine=_LadderFake("wide", buckets=(4, 64)),
                    weight=1.0)
            reg.add("plain", engine=_LadderFake("plain"), weight=0.0)
            assert reg.variant("wide").batcher.max_batch == 64
            # no ladder on the engine → the registry default's top
            assert reg.variant("plain").batcher.max_batch == 8
        finally:
            reg.close()

    def test_status_surfaces_buckets_and_histogram_rows(self):
        reg = _registry()
        try:
            reg.add("v", engine=_LadderFake("v", buckets=(2, 16)),
                    weight=1.0)
            reg.variant("v").histogram.record("sample", 5)
            snap = reg.snapshot()["variants"]["v"]
            assert snap["buckets"] == [2, 16]
            assert snap["histogram_rows"] == 1
        finally:
            reg.close()

    def test_adoption_inherits_incumbent_traffic_shape(self):
        # the generation that inherits the traffic inherits its learned
        # shape: the incumbent primary's flush histogram folds into the
        # newcomer's on adopt (ISSUE 19 carry-forward)
        reg = _registry()
        try:
            reg.add("gen-1", engine=_LadderFake("gen-1"), weight=1.0)
            reg.variant("gen-1").histogram.record("sample", 3)
            reg.variant("gen-1").histogram.record("sample", 3)
            reg.adopt("gen-2", _LadderFake("gen-2"), weight=0.0)
            assert reg.variant("gen-2").histogram.merged() == {3: 2}
            # a copy, not shared state: new traffic diverges
            reg.variant("gen-2").histogram.record("sample", 9)
            assert reg.variant("gen-1").histogram.merged() == {3: 2}
        finally:
            reg.close()


# ===========================================================================
# reload plane — resolution order + learned solve
# ===========================================================================

class TestReloaderLadder:
    def test_priority_manifest_then_learned_then_incumbent(self):
        assert _ladder_priority((1, 4), (2, 8), (1, 8)) == (1, 4)
        assert _ladder_priority(None, (2, 8), (1, 8)) == (2, 8)
        assert _ladder_priority(None, None, (1, 8)) == (1, 8)

    def _controller(self, histogram):
        service = types.SimpleNamespace(
            batcher=types.SimpleNamespace(size_histogram=histogram))
        watcher = types.SimpleNamespace(path=None)
        return ReloadController(service, watcher, poll_interval=1.0)

    def test_learned_buckets_solves_under_incumbent_contract(self):
        h = SizeHistogram()
        for _ in range(50):
            h.record("sample", 3)
        live = types.SimpleNamespace(buckets=(1, 8, 32, 128))
        ladder = self._controller(h)._learned_buckets(live)
        # budget = len(incumbent ladder), top = incumbent top: the
        # chunking contract (max_batch, bulk lane) survives the swap
        assert ladder is not None
        assert ladder[-1] == 128 and len(ladder) <= 4
        assert 3 in ladder

    def test_learned_buckets_none_when_nothing_recorded(self):
        ctl = self._controller(SizeHistogram())
        assert ctl._learned_buckets(
            types.SimpleNamespace(buckets=(1, 8))) is None
        assert ctl._learned_buckets(None) is None

    def test_learned_buckets_swallows_solver_failure(self):
        # a reload must never fail over ladder learning
        class Boom:
            def merged(self):
                raise RuntimeError("solver hiccup")

        ctl = self._controller(Boom())
        assert ctl._learned_buckets(
            types.SimpleNamespace(buckets=(1, 8))) is None


# ===========================================================================
# fleet — compilation-cache propagation (warm elasticity)
# ===========================================================================

class TestFleetCompilationCache:
    def test_worker_cmd_carries_cache_flag(self, tmp_path):
        m = FleetManager(FleetRouter(), str(tmp_path), num_workers=1,
                         ports=[1], spawn=lambda slot, bundle: None,
                         compilation_cache=str(tmp_path / "xla"))
        cmd = m._worker_cmd(m.slots[0], "/bundle")
        i = cmd.index("--compilation-cache")
        assert cmd[i + 1] == str(tmp_path / "xla")
        assert m.status()["compilation_cache"] == str(tmp_path / "xla")

    def test_worker_cmd_omits_flag_when_unset(self, tmp_path):
        m = FleetManager(FleetRouter(), str(tmp_path), num_workers=1,
                         ports=[2], spawn=lambda slot, bundle: None)
        assert "--compilation-cache" not in m._worker_cmd(
            m.slots[0], "/bundle")
        assert m.status()["compilation_cache"] is None

    def test_launch_resets_routable_clock(self, tmp_path):
        m = FleetManager(FleetRouter(), str(tmp_path), num_workers=1,
                         ports=[3],
                         spawn=lambda slot, bundle:
                         types.SimpleNamespace(pid=1234))
        slot = m.slots[0]
        slot.routable_s = 1.23  # stale timing from a dead process
        m._launch(slot, "/bundle")
        # the NEW process re-earns its launch→routable timing
        assert slot.routable_s is None
