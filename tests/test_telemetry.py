"""Unified telemetry plane tests (docs/OBSERVABILITY.md).

Covers the ISSUE-6 acceptance surface: trace-export schema validity (every
event carries ph/ts/pid/tid, spans nest, correlation ids survive the
batcher's two-stage pipeline), registry thread-safety under the batcher's
worker+completer threads, a Prometheus exposition golden test, the
overhead smoke (telemetry-off serve path allocates no registry series and
records no events; telemetry-on stays inside the 5%-of-wall budget on a
sleep-dominated fake engine), the serving surface (`generation` in
/healthz and /metrics, `?format=prom`, /debug/spans, /debug/trace device
captures, SIGUSR2), and the end-to-end drive: one Chrome trace showing a
supervisor training segment publishing a generation and a serving request
consuming it, with correlated spans across both planes.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.serving import (
    InferenceService,
    MicroBatcher,
    ServingEngine,
    make_server,
)
from gan_deeplearning4j_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
    percentiles,
)
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    Tracer,
    bind_trace_id,
    new_trace_id,
    unbind_trace_id,
)
from gan_deeplearning4j_tpu.utils import write_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES = 4, 6, 3


def _checkpoints(tmp_path):
    b = GraphBuilder(GraphConfig(seed=1))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    gen = b.build()
    b = GraphBuilder(GraphConfig(seed=2))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=5), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    cv = b.build()
    gen_path = str(tmp_path / "gen.zip")
    cv_path = str(tmp_path / "cv.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    write_model(cv_path, cv, cv.init(), save_updater=False)
    return gen_path, cv_path


# ===========================================================================
# one percentile definition across the repo
# ===========================================================================

class TestOneDefinition:
    def test_profiling_percentiles_is_the_registry_function(self):
        from gan_deeplearning4j_tpu.utils import profiling

        assert profiling.percentiles is percentiles

    def test_nearest_rank_contract_unchanged(self):
        # the PR 3 definition: nearest-rank over sorted samples
        assert percentiles([4.0, 1.0, 3.0, 2.0], (50,)) == {"p50": 2.0}
        assert percentiles([], (50,)) == {}
        out = percentiles(range(1, 101))
        assert out == {"p50": 50.0, "p95": 95.0, "p99": 99.0}


# ===========================================================================
# metrics registry
# ===========================================================================

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help", labelnames=("kind",))
        c.labels(kind="a").inc()
        c.labels(kind="a").inc(2)
        c.labels(kind="b").inc()
        assert c.labels(kind="a").value == 3
        assert c.labels(kind="b").value == 1
        g = reg.gauge("g")
        g.set(5)
        g.dec(2)
        assert g.labels().value == 3
        h = reg.histogram("h")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        child = h.labels()
        assert child.count == 3 and abs(child.total - 0.6) < 1e-9
        assert child.percentiles((50,)) == {"p50": 0.2}

    def test_reregistration_is_idempotent_conflict_raises(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", labelnames=("kind",))
        b = reg.counter("x_total", labelnames=("kind",))
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("x_total", labelnames=("other",))

    def test_counters_only_go_up(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("c_total").labels().inc(-1)

    def test_unknown_labels_rejected(self):
        reg = MetricsRegistry()
        fam = reg.counter("c_total", labelnames=("kind",))
        with pytest.raises(ValueError, match="expected labels"):
            fam.labels(wrong="x")

    def test_thread_safety_under_concurrent_writers(self):
        """The batcher updates series from its worker AND completer
        threads; `x += 1` interleaves at the bytecode level, so the series
        lock must make every increment land."""
        reg = MetricsRegistry()
        child = reg.counter("t_total", labelnames=("kind",)).labels(kind="x")
        hist = reg.histogram("t_seconds").labels()
        n, per = 8, 5000

        def work():
            for _ in range(per):
                child.inc()
                hist.observe(0.001)

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value == n * per
        assert hist.count == n * per

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "help", labelnames=("kind",)).labels(
            kind="a").inc()
        reg.histogram("h_seconds").observe(0.5)
        snap = reg.snapshot()
        assert snap["c_total"]["type"] == "counter"
        assert snap["c_total"]["series"] == [
            {"labels": {"kind": "a"}, "value": 1.0}]
        hrow = snap["h_seconds"]["series"][0]
        assert hrow["count"] == 1 and hrow["p50"] == 0.5


class TestPrometheus:
    def test_golden_exposition(self):
        reg = MetricsRegistry()
        reg.histogram("lat_seconds", "lat").observe(0.25)
        reg.gauge("queue_depth", "depth").set(2)
        reg.counter("requests_total", "reqs",
                    labelnames=("kind", "status")).labels(
            kind="sample", status="ok").inc(3)
        expected = (
            '# HELP lat_seconds lat\n'
            '# TYPE lat_seconds summary\n'
            'lat_seconds{quantile="0.5"} 0.25\n'
            'lat_seconds{quantile="0.95"} 0.25\n'
            'lat_seconds{quantile="0.99"} 0.25\n'
            'lat_seconds_sum 0.25\n'
            'lat_seconds_count 1\n'
            '# HELP queue_depth depth\n'
            '# TYPE queue_depth gauge\n'
            'queue_depth 2\n'
            '# HELP requests_total reqs\n'
            '# TYPE requests_total counter\n'
            'requests_total{kind="sample",status="ok"} 3\n'
        )
        assert reg.to_prometheus() == expected

    def test_label_values_escaped(self):
        reg = MetricsRegistry()
        reg.counter("e_total", labelnames=("why",)).labels(
            why='say "hi"\\\n').inc()
        text = reg.to_prometheus()
        assert r'why="say \"hi\"\\\n"' in text

    def test_metric_names_sanitized(self):
        reg = MetricsRegistry()
        reg.counter("bad-name.total").inc()
        assert "bad_name_total 1" in reg.to_prometheus()


# ===========================================================================
# span tracer
# ===========================================================================

class TestTracer:
    def test_span_event_schema(self):
        tr = Tracer(enabled=True)
        with tr.span("work", gen=7):
            time.sleep(0.002)
        (ev,) = tr.events()
        for field in ("name", "ph", "ts", "pid", "tid", "dur"):
            assert field in ev
        assert ev["ph"] == "X" and ev["name"] == "work"
        assert ev["pid"] == os.getpid()
        assert ev["dur"] >= 2000  # µs
        assert ev["args"]["gen"] == 7

    def test_spans_nest(self):
        tr = Tracer(enabled=True)
        with tr.span("outer"):
            time.sleep(0.001)
            with tr.span("inner"):
                time.sleep(0.001)
            time.sleep(0.001)
        by_name = {e["name"]: e for e in tr.events()}
        outer, inner = by_name["outer"], by_name["inner"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0

    def test_disabled_records_nothing_and_allocates_no_span(self):
        tr = Tracer()
        assert tr.span("a") is tr.span("b")  # the shared no-op object
        with tr.span("a"):
            pass
        tr.complete("x", 0.0, 1.0)
        tr.instant("y")
        tr.async_begin("z", "1")
        tr.async_end("z", "1")
        assert len(tr) == 0 and tr.events() == []

    def test_ring_buffer_bounds_memory(self):
        tr = Tracer(capacity=4, enabled=True)
        for i in range(10):
            tr.instant(f"e{i}")
        assert len(tr) == 4
        assert tr.dropped == 6
        assert [e["name"] for e in tr.events()] == ["e6", "e7", "e8", "e9"]

    def test_async_begin_end_pair(self):
        tr = Tracer(enabled=True)
        tr.async_begin("flight", "f-1", {"kind": "sample"})
        tr.async_end("flight", "f-1", {"status": "ok"})
        b, e = tr.events()
        assert (b["ph"], e["ph"]) == ("b", "e")
        assert b["id"] == e["id"] == "f-1"

    def test_contextvar_correlation_lands_in_args(self):
        tr = Tracer(enabled=True)
        token = bind_trace_id("req-42")
        try:
            tr.instant("hop")
        finally:
            unbind_trace_id(token)
        tr.instant("after")
        hop, after = tr.events()
        assert hop["args"]["trace_id"] == "req-42"
        assert "args" not in after or "trace_id" not in after.get("args", {})

    def test_dump_writes_loadable_chrome_trace(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("alpha"):
            pass
        path = tr.dump(str(tmp_path / "t.json"), {"source": "test"})
        with open(path) as fh:
            doc = json.load(fh)
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        assert doc["metadata"]["source"] == "test"
        assert doc["displayTimeUnit"] == "ms"

    def test_trace_ids_are_process_unique(self):
        a, b = new_trace_id(), new_trace_id()
        assert a != b and a.startswith(f"{os.getpid():x}-")


# ===========================================================================
# batcher pipeline: correlation survives worker+completer threads
# ===========================================================================

class TestBatcherTelemetry:
    def _drive(self, n=4):
        TRACER.enable()
        mb = MicroBatcher(run_fn=lambda k, r: r, max_batch=8,
                          max_latency=0.0)
        token = bind_trace_id("req-under-test")
        try:
            for _ in range(n):
                res = mb.submit("k", np.ones((1, 3), np.float32))
                assert res.ok
        finally:
            unbind_trace_id(token)
        mb.close()
        return mb, TRACER.events()

    def test_all_pipeline_stages_emit_spans(self):
        _, events = self._drive()
        names = {e["name"] for e in events}
        assert {"serve.batcher.submit", "serve.batcher.cut",
                "serve.batcher.dispatch", "serve.batcher.finalize",
                "serve.batcher.scatter", "serve.flight"} <= names

    def test_correlation_id_survives_both_thread_handoffs(self):
        """submit (caller thread) → cut/dispatch (worker thread) →
        finalize/scatter (completer thread): the id minted at submit must
        appear in every stage's args even though contextvars do not cross
        threads."""
        _, events = self._drive(n=1)
        by_name = {e["name"]: e for e in events}
        rid = by_name["serve.batcher.submit"]["args"]["trace_id"]
        assert rid == "req-under-test"
        assert rid in by_name["serve.batcher.cut"]["args"]["riders"]
        assert rid in by_name["serve.batcher.dispatch"]["args"]["riders"]
        assert rid in by_name["serve.batcher.scatter"]["args"]["riders"]
        # and the stages really ran on three distinct threads
        tids = {by_name[n]["tid"] for n in
                ("serve.batcher.submit", "serve.batcher.cut",
                 "serve.batcher.scatter")}
        assert len(tids) == 3

    def test_flight_async_pair_brackets_the_flush(self):
        _, events = self._drive(n=1)
        begins = [e for e in events
                  if e["name"] == "serve.flight" and e["ph"] == "b"]
        ends = [e for e in events
                if e["name"] == "serve.flight" and e["ph"] == "e"]
        assert len(begins) == len(ends) == 1
        assert begins[0]["id"] == ends[0]["id"]
        assert begins[0]["tid"] != ends[0]["tid"]  # worker vs completer

    def test_every_event_is_schema_valid(self):
        _, events = self._drive()
        for ev in events:
            for field in ("name", "ph", "ts", "pid", "tid"):
                assert field in ev, ev
            if ev["ph"] == "X":
                assert "dur" in ev

    def test_latency_percentiles_come_from_the_registry_histogram(self):
        mb, _ = self._drive(n=6)
        fam = get_registry().histogram(
            "serve_request_latency_seconds",
            labelnames=("kind",))
        child = fam.labels(kind="k")
        assert child.count == 6
        lat = mb.metrics()["latency_ms"]["k"]
        assert set(lat) == {"p50", "p95", "p99"}
        assert abs(lat["p50"] - child.percentiles((50,))["p50"] * 1e3) < 1e-9

    def test_registry_counters_mirror_the_ledger(self):
        mb, _ = self._drive(n=5)
        snap = get_registry().snapshot()
        ok_rows = [s for s in snap["serve_requests_total"]["series"]
                   if s["labels"] == {"kind": "k", "status": "ok"}]
        assert ok_rows and ok_rows[0]["value"] == 5
        assert snap["serve_flushes_total"]["series"][0]["value"] == \
            mb.metrics()["flushes"]
        assert "serve_stage_seconds" in snap

    def test_metrics_json_schema_is_preserved(self):
        mb, _ = self._drive()
        m = mb.metrics()
        for key in ("submitted", "completed", "shed_overloaded",
                    "shed_deadline", "errors", "flushes", "queue_depth",
                    "batch_occupancy", "latency_ms", "pipeline"):
            assert key in m
        assert m["submitted"] == {"k": 4} and m["completed"] == {"k": 4}


# ===========================================================================
# overhead smoke: off = nothing; on = inside the 5% budget
# ===========================================================================

class TestOverhead:
    def test_disabled_path_allocates_no_registry_series_or_events(self):
        mb = MicroBatcher(run_fn=lambda k, r: r, max_batch=8,
                          max_latency=0.0)
        # warm: the first request of a kind creates its series once
        assert mb.submit("k", np.ones((1, 3), np.float32)).ok
        reg = get_registry()
        baseline = reg.series_count()
        for _ in range(25):
            assert mb.submit("k", np.ones((1, 3), np.float32)).ok
        mb.close()
        assert reg.series_count() == baseline  # steady state: no new series
        assert len(TRACER) == 0  # tracing off: nothing recorded
        assert TRACER.span("a") is TRACER.span("b")  # no span objects either

    def test_enabled_overhead_within_budget_on_fake_engine(self):
        """Paired off/on rounds over a sleep-dominated fake engine (the
        pipelining tests' workload shape). Budget: telemetry-on within 5%
        of wall, with an absolute floor of 500 µs/request. Timing noise on
        a loaded CI box only ever ADDS time, so each estimate is the MIN
        of several alternating rounds, and a noisy attempt (where even the
        mins were perturbed) gets retried — the test proves an upper bound
        on overhead exists, and one clean measurement suffices for that;
        real per-request cost (a handful of dict/event appends, ~tens of
        µs) sits an order of magnitude under the gate."""
        n = 40
        rows = np.ones((1, 3), np.float32)

        def run_round(enabled):
            if enabled:
                TRACER.enable()
            else:
                TRACER.disable()
            mb = MicroBatcher(
                run_fn=lambda k, r: (time.sleep(0.002), r)[1],
                max_batch=8, max_latency=0.0)
            t0 = time.perf_counter()
            for _ in range(n):
                assert mb.submit("k", rows).ok
            elapsed = time.perf_counter() - t0
            mb.close()
            return elapsed

        on = off = per_request = 0.0
        for attempt in range(3):
            offs, ons = [], []
            for _ in range(3):
                offs.append(run_round(False))
                ons.append(run_round(True))
            TRACER.disable()
            off, on = min(offs), min(ons)
            per_request = (on - off) / n
            if on <= off * 1.05 or per_request < 500e-6:
                return
        assert on <= off * 1.05 or per_request < 500e-6, (
            f"telemetry-on {on:.4f}s vs off {off:.4f}s "
            f"({per_request * 1e6:.0f}µs/request over budget in all "
            f"attempts)")


# ===========================================================================
# serving surface: generation, prom exposition, debug hooks
# ===========================================================================

class TestServingSurface:
    def _service(self, tmp_path, generation=None, **kw):
        gen_path, cv_path = _checkpoints(tmp_path)
        engine = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path, buckets=(1, 8),
            feature_vertex="feat_1", generation=generation,
        )
        return InferenceService(engine, warmup=False, max_latency=0.0, **kw)

    def test_generation_surfaces_in_healthz_and_metrics(self, tmp_path):
        svc = self._service(tmp_path, generation=7)
        try:
            assert svc.healthz()["generation"] == 7
            m = svc.metrics()
            assert m["generation"] == 7
            assert m["engine"]["generation"] == 7
            snap = get_registry().snapshot()
            assert snap["serving_generation"]["series"][0]["value"] == 7
        finally:
            svc.close()

    def test_unversioned_engine_reports_generation_none(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            assert svc.healthz()["generation"] is None
            assert svc.metrics()["generation"] is None
        finally:
            svc.close()

    def test_prometheus_exposition_over_http(self, tmp_path):
        import urllib.request

        svc = self._service(tmp_path, generation=3)
        server = make_server(svc, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?format=prom",
                    timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "# TYPE serve_queue_depth gauge" in text
            assert "serving_generation 3" in text
            # the JSON payload still answers without the format knob
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                body = json.loads(r.read())
            assert body["generation"] == 3
        finally:
            server.shutdown()
            server.server_close()
            svc.close()

    def test_debug_spans_exports_chrome_trace(self, tmp_path):
        TRACER.enable()
        svc = self._service(tmp_path)
        try:
            TRACER.instant("marker")
            status, body = svc.handle("GET", "/debug/spans")
            assert status == 200
            assert any(e["name"] == "marker" for e in body["traceEvents"])
        finally:
            svc.close()

    def test_debug_trace_captures_device_profile(self, tmp_path):
        """Async by default: 202 answers immediately with the path the
        artifact WILL land at (cold profiler start/stop can take tens of
        seconds — no HTTP client should wait through that)."""
        artifacts = str(tmp_path / "captures")
        svc = self._service(tmp_path, artifacts_dir=artifacts)
        try:
            status, body = svc.handle("POST", "/debug/trace?ms=40")
            assert status == 202, body
            out = body["artifact"]
            assert out.startswith(artifacts)
            deadline = time.monotonic() + 120.0
            captured = []
            while time.monotonic() < deadline and not captured:
                captured = [
                    os.path.join(root, f)
                    for root, _, files in os.walk(out) for f in files
                ]
                time.sleep(0.05)
            assert captured, "capture produced no profiler artifacts"
        finally:
            svc.close()

    def test_debug_trace_block_mode_waits_for_the_artifact(self, tmp_path):
        artifacts = str(tmp_path / "captures_block")
        svc = self._service(tmp_path, artifacts_dir=artifacts)
        try:
            status, body = svc.handle("POST", "/debug/trace?ms=30&block=1")
            assert status == 200, body
            out = body["artifact"]
            assert os.path.isdir(out)
            assert any(files for _, _, files in os.walk(out))
        finally:
            svc.close()

    def test_debug_trace_rejects_bad_duration(self, tmp_path):
        svc = self._service(tmp_path, artifacts_dir=str(tmp_path / "c"))
        try:
            assert svc.handle("POST", "/debug/trace?ms=nope")[0] == 400
            assert svc.handle("POST", "/debug/trace?ms=0")[0] == 400
            assert svc.handle("POST", "/debug/trace?ms=999999")[0] == 400
        finally:
            svc.close()


class TestSignalCapture:
    def test_sigusr2_triggers_background_capture(self, tmp_path):
        from gan_deeplearning4j_tpu.telemetry.device import (
            install_signal_capture,
        )

        artifacts = str(tmp_path / "sig")
        old = signal.getsignal(signal.SIGUSR2)
        try:
            install_signal_capture(artifacts, duration_ms=30)
            os.kill(os.getpid(), signal.SIGUSR2)
            deadline = time.monotonic() + 10.0
            files = []
            while time.monotonic() < deadline and not files:
                files = [
                    os.path.join(root, f)
                    for root, _, fs in os.walk(artifacts) for f in fs
                ]
                time.sleep(0.05)
            assert files, "SIGUSR2 produced no capture artifacts"
        finally:
            signal.signal(signal.SIGUSR2, old)

    def test_path_failure_does_not_strand_the_capture_lock(self, monkeypatch):
        # regression (JG027 lifecycle audit): capture_async composes the
        # output path BEFORE taking the capture lock — if that step raised
        # after the acquire there would be no thread to release, and every
        # later capture would 409 forever
        from gan_deeplearning4j_tpu.telemetry import device

        def boom(_artifacts_dir):
            raise OSError("disk gone")

        monkeypatch.setattr(device, "_capture_dir", boom)
        with pytest.raises(OSError):
            device.capture_async("anywhere")
        assert device._capture_lock.acquire(blocking=False), (
            "capture lock left held after a failed capture_async")
        device._capture_lock.release()


# ===========================================================================
# trace_report: the campaign gate
# ===========================================================================

class TestTraceReport:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             *argv],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )

    def test_folds_a_real_trace(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("serve.batcher.dispatch", kind="sample"):
            time.sleep(0.002)
        with tr.span("serve.batcher.finalize", kind="sample"):
            time.sleep(0.001)
        path = tr.dump(str(tmp_path / "trace.json"))
        proc = self._run(path, "--json", str(tmp_path / "report.json"))
        assert proc.returncode == 0, proc.stderr
        assert "serve.batcher.dispatch" in proc.stdout
        with open(tmp_path / "report.json") as fh:
            report = json.load(fh)
        assert report["spans"] == 2
        assert report["phases"]["serve.batcher.dispatch"]["count"] == 1

    def test_empty_trace_fails_the_gate(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text('{"traceEvents": []}\n')
        proc = self._run(str(path))
        assert proc.returncode == 1
        assert "no complete spans" in proc.stderr

    def test_alert_overlay_joins_the_timeline(self, tmp_path):
        # --alerts: the incident ring lands as instant events on the
        # same wall-epoch timeline the spans use, the merged artifact
        # keeps them, and the report summarizes the transitions
        tr = Tracer(enabled=True)
        with tr.span("fleet.route", path="/v1/sample"):
            time.sleep(0.002)
        trace = tr.dump(str(tmp_path / "trace.json"))
        alerts = tmp_path / "alerts.json"
        alerts.write_text(json.dumps({"incidents": [
            {"t": time.time(), "alert": "worker_down", "severity": "page",
             "labels": {"worker": "w0"}, "from": "pending", "to": "firing"},
            {"t": time.time(), "alert": "worker_down", "severity": "page",
             "labels": {"worker": "w0"}, "from": "firing",
             "to": "resolved"},
        ]}))
        merged = tmp_path / "merged.json"
        proc = self._run(trace, "--alerts", str(alerts),
                         "--merge-out", str(merged),
                         "--json", str(tmp_path / "report.json"))
        assert proc.returncode == 0, proc.stderr
        assert "alert overlay:" in proc.stdout
        assert "pending -> firing" in proc.stdout
        with open(tmp_path / "report.json") as fh:
            report = json.load(fh)
        assert report["alerts"] == {"transitions": 2,
                                    "by_state": {"firing": 1,
                                                 "resolved": 1}}
        with open(merged) as fh:
            events = json.load(fh)["traceEvents"]
        assert sum(1 for e in events
                   if str(e.get("name", "")).startswith("alert:")) == 2

    def test_alert_overlay_rejects_non_alert_file(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("x"):
            pass
        trace = tr.dump(str(tmp_path / "trace.json"))
        bad = tmp_path / "notalerts.json"
        bad.write_text('{"rules": []}\n')
        proc = self._run(trace, "--alerts", str(bad))
        assert proc.returncode == 1
        assert "incidents" in proc.stderr

    def test_malformed_trace_fails_the_gate(self, tmp_path):
        path = tmp_path / "broken.json"
        path.write_text("{not json\n")
        assert self._run(str(path)).returncode == 1
        path2 = tmp_path / "schema.json"
        path2.write_text(json.dumps(
            {"traceEvents": [{"name": "x", "ph": "X", "ts": 1.0}]}))
        proc = self._run(str(path2))
        assert proc.returncode == 1
        assert "missing" in proc.stderr

    def test_missing_file_fails_the_gate(self, tmp_path):
        assert self._run(str(tmp_path / "nope.json")).returncode == 1


# ===========================================================================
# the end-to-end drive: train → publish generation → serve it, one trace
# ===========================================================================

class TestEndToEndTrace:
    def test_supervisor_publish_and_serving_consume_share_one_trace(
            self, tmp_path):
        """ISSUE-6 acceptance: a supervisor training segment publishes a
        generation, a serving engine loads that generation and answers a
        request, and ONE Chrome trace holds correlated spans from both
        planes (the publish span and the serving plane agree on the
        generation number; the request's correlation id crosses the
        batcher pipeline)."""
        from gan_deeplearning4j_tpu.harness import (
            ExperimentConfig,
            GanExperiment,
        )
        from gan_deeplearning4j_tpu.resilience import (
            SupervisorConfig,
            TrainingSupervisor,
        )

        TRACER.enable()
        cfg = ExperimentConfig(
            model_family="tabular", num_features=16, z_size=4,
            batch_size_train=8, batch_size_pred=8,
            height=1, width=1, channels=1,
            save_models=False,
            output_dir=os.path.join(str(tmp_path), "out"),
        )
        rng = np.random.default_rng(0)
        feats = rng.random((16, 16), dtype=np.float32)
        labels = np.eye(10, dtype=np.float32)[np.arange(16) % 10]

        sup = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=2, publish_every=2),
            feats, labels,
            store_root=os.path.join(str(tmp_path), "store"))
        summary = sup.run()
        assert summary["status"] == "completed"

        # restore the trained state and publish a SERVING bundle as the
        # next store generation — the artifact a live server would poll
        exp = GanExperiment(cfg)
        exp.load_models(directory=sup.store.latest_valid().path)
        published = exp.publish_for_serving(store=sup.store)
        serving_gen = published["generation"]
        assert serving_gen is not None

        engine = ServingEngine.from_bundle(
            published["directory"], buckets=(1, 4))
        service = InferenceService(engine, warmup="sync", max_latency=0.0)
        try:
            assert service.healthz()["generation"] == serving_gen
            status, body = service.handle(
                "POST", "/v1/sample",
                {"data": (rng.random((1, 4)) * 2 - 1).tolist()})
            assert status == 200 and body["status"] == "ok"
        finally:
            service.close()

        trace = TRACER.chrome_trace({"drive": "e2e"})
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        # both planes in one trace
        assert {"resilience.step", "resilience.segment",
                "resilience.publish"} <= names
        assert {"serve.engine.restore", "serve.request",
                "serve.batcher.dispatch", "serve.batcher.scatter"} <= names
        # correlated: the serving bundle's publish span carries the SAME
        # generation number the serving plane reports
        publish_gens = {e["args"]["gen"] for e in events
                        if e["name"] == "resilience.publish"}
        assert serving_gen in publish_gens
        restore = next(e for e in events
                       if e["name"] == "serve.engine.restore")
        assert restore["args"]["generation"] == serving_gen
        # and the HTTP request's correlation id crossed the pipeline
        request = next(e for e in events if e["name"] == "serve.request")
        rid = request["args"]["trace_id"]
        scatter = next(e for e in events
                       if e["name"] == "serve.batcher.scatter")
        assert rid in scatter["args"]["riders"]

        # the trace is a valid, foldable artifact — the campaign gate
        path = str(tmp_path / "e2e_trace.json")
        TRACER.dump(path)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"), path],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "resilience.publish" in proc.stdout


# ===========================================================================
# fleet aggregation: merge semantics (ISSUE-11)
# ===========================================================================

class TestFleetAggregate:
    def _snap(self, build):
        reg = MetricsRegistry()
        build(reg)
        return reg.snapshot(include_samples=True)

    def test_counters_sum_exactly(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        def w0(reg):
            fam = reg.counter("serve_requests_total", "x",
                              labelnames=("kind", "status"))
            fam.labels(kind="sample", status="ok").inc(7)
            fam.labels(kind="classify", status="ok").inc(2)

        def w1(reg):
            fam = reg.counter("serve_requests_total", "x",
                              labelnames=("kind", "status"))
            fam.labels(kind="sample", status="ok").inc(5)
            fam.labels(kind="sample", status="error").inc(1)

        merged = merge_snapshots({"w0": self._snap(w0), "w1": self._snap(w1)})
        series = {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in merged["serve_requests_total"]["series"]
        }
        assert series[(("kind", "sample"), ("status", "ok"))] == 12
        assert series[(("kind", "classify"), ("status", "ok"))] == 2
        assert series[(("kind", "sample"), ("status", "error"))] == 1

    def test_counter_exactness_under_concurrent_scrapes(self):
        """The merge math loses nothing: while N threads hammer two live
        registries, every (scrape both → merge) sample equals the sum of
        the two per-registry scraped values EXACTLY — aggregation is
        arithmetic over atomic snapshots, not estimation."""
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        regs = [MetricsRegistry(), MetricsRegistry()]
        counters = [r.counter("c", "x").labels() for r in regs]
        stop = threading.Event()

        def hammer(c):
            while not stop.is_set():
                c.inc()

        threads = [threading.Thread(target=hammer, args=(c,), daemon=True)
                   for c in counters for _ in range(2)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                snaps = [r.snapshot(include_samples=True) for r in regs]
                expected = sum(
                    s["c"]["series"][0]["value"] for s in snaps)
                merged = merge_snapshots({"a": snaps[0], "b": snaps[1]})
                assert merged["c"]["series"][0]["value"] == expected
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)

    def test_gauges_labeled_per_worker_not_summed(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        def w(depth):
            def build(reg):
                reg.gauge("serve_queue_depth", "x").set(depth)
            return build

        merged = merge_snapshots({"w0": self._snap(w(3)),
                                  "w1": self._snap(w(0))})
        series = {s["labels"]["worker"]: s["value"]
                  for s in merged["serve_queue_depth"]["series"]}
        assert series == {"w0": 3.0, "w1": 0.0}

    def test_histogram_percentile_parity_vs_single_stream(self):
        """The acceptance property: percentiles of the merged histogram
        equal percentiles of one histogram that observed ALL the values —
        the nearest-rank contract holds fleet-wide because the merge
        pools raw samples instead of averaging quantiles."""
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        rng = np.random.default_rng(11)
        values = rng.exponential(0.05, size=301)
        split = 117

        def member(chunk):
            def build(reg):
                h = reg.histogram("lat", "x").labels()
                for v in chunk:
                    h.observe(float(v))
            return build

        merged = merge_snapshots({
            "w0": self._snap(member(values[:split])),
            "w1": self._snap(member(values[split:])),
        })
        got = merged["lat"]["series"][0]
        want = percentiles([float(v) for v in values])
        assert got["count"] == len(values)
        assert got["sum"] == pytest.approx(float(values.sum()))
        for key in ("p50", "p95", "p99"):
            assert got[key] == want[key]

    def test_histogram_merge_with_empty_samples_member(self):
        """A truncated scrape: one member reports count/sum but an EMPTY
        samples list. The merge must pool the non-empty members'
        samples for the percentiles (not crash, not skew toward zero)
        while count/sum stay the exact fleet totals."""
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        full = {"lat": {"type": "histogram", "help": "", "series": [
            {"labels": {}, "count": 4, "sum": 4.0,
             "samples": [0.5, 1.0, 1.0, 1.5]}]}}
        truncated = {"lat": {"type": "histogram", "help": "", "series": [
            {"labels": {}, "count": 3, "sum": 30.0, "samples": []}]}}
        merged = merge_snapshots({"w0": full, "w1": truncated})
        [series] = merged["lat"]["series"]
        assert series["count"] == 7        # totals are exact
        assert series["sum"] == 34.0
        # percentiles describe the pooled NON-EMPTY samples: w1's much
        # slower (but unsampled) traffic cannot drag them to zero or NaN
        assert series["p50"] == 1.0
        assert series["p99"] == 1.5

    def test_histogram_merge_all_members_sampleless(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        part = {"lat": {"type": "histogram", "help": "", "series": [
            {"labels": {}, "count": 2, "sum": 6.0, "samples": []}]}}
        merged = merge_snapshots({"w0": part, "w1": part})
        [series] = merged["lat"]["series"]
        assert series["count"] == 4 and series["sum"] == 12.0
        # no samples anywhere: no percentile keys, not a crash and not 0s
        assert not any(k.startswith("p") for k in series
                       if k not in ("labels",))

    def test_histogram_merge_missing_samples_key(self):
        # a member snapshotted without include_samples (samples key
        # absent entirely) contributes count/sum only
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        with_samples = {"lat": {"type": "histogram", "help": "", "series": [
            {"labels": {}, "count": 2, "sum": 2.0, "samples": [0.9, 1.1]}]}}
        without = {"lat": {"type": "histogram", "help": "", "series": [
            {"labels": {}, "count": 5, "sum": 5.0}]}}
        merged = merge_snapshots({"w0": with_samples, "w1": without})
        [series] = merged["lat"]["series"]
        assert series["count"] == 7 and series["sum"] == 7.0
        assert series["p50"] == 0.9

    def test_gauge_keeps_its_own_worker_label(self):
        # the router's per-member gauges (fleet_member_routable/...)
        # already NAME the member each fact describes: the merge must
        # fill the worker label only where it is missing, never relabel
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        part = {"fleet_member_routable": {"type": "gauge", "help": "",
                                          "series": [
            {"labels": {"worker": "w7"}, "value": 0.0}]}}
        merged = merge_snapshots({"router": part})
        [series] = merged["fleet_member_routable"]["series"]
        assert series["labels"] == {"worker": "w7"}

    def test_partial_fleet_scrape_degrades_to_labeled_gap(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
        )

        def w(reg):
            reg.counter("c", "x").inc(4)

        merged = merge_snapshots({"w0": self._snap(w)}, gaps=["w1", "w2"])
        up = {s["labels"]["worker"]: s["value"]
              for s in merged["fleet_member_up"]["series"]}
        assert up == {"w0": 1.0, "w1": 0.0, "w2": 0.0}
        assert merged["_fleet"]["gaps"] == ["w1", "w2"]
        assert merged["c"]["series"][0]["value"] == 4

    def test_malformed_member_never_crashes(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import merge_snapshots

        def w(reg):
            reg.counter("c", "x").inc(1)

        merged = merge_snapshots({
            "good": self._snap(w),
            "junk": ["not", "a", "snapshot"],
            "halfjunk": {"c": {"type": "gauge",
                               "series": [{"labels": {}, "value": 9}]},
                         "bad": 42},
        })
        # good's counter survives; junk members land in conflicts
        assert merged["c"]["series"][0]["value"] == 1
        conflicts = "\n".join(merged["_fleet"]["conflicts"])
        assert "junk" in conflicts and "halfjunk" in conflicts

    def test_prometheus_rendering_of_merged_snapshot(self):
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
            snapshot_to_prometheus,
        )

        def w0(reg):
            reg.counter("fleet_c", "help text").inc(3)
            reg.histogram("lat", "l").labels().observe(0.25)

        def w1(reg):
            reg.counter("fleet_c", "help text").inc(4)

        text = snapshot_to_prometheus(merge_snapshots(
            {"w0": self._snap(w0), "w1": self._snap(w1)}, gaps=["w2"]))
        assert "# TYPE fleet_c counter" in text
        assert "fleet_c 7" in text
        assert 'lat{quantile="0.5"} 0.25' in text
        assert "lat_count 1" in text
        assert 'fleet_member_up{worker="w2"} 0' in text
        assert "_fleet" not in text  # metadata never leaks into exposition

    def test_fmt_handles_nan_and_inf(self):
        from gan_deeplearning4j_tpu.telemetry.registry import _fmt

        assert _fmt(float("nan")) == "NaN"
        assert _fmt(float("inf")) == "+Inf"
        assert _fmt(float("-inf")) == "-Inf"
        assert _fmt(3.0) == "3"

    def test_registry_snapshot_include_samples(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", "x").labels()
        h.observe(1.0)
        h.observe(2.0)
        assert "samples" not in reg.snapshot()["h"]["series"][0]
        assert reg.snapshot(include_samples=True)["h"]["series"][0][
            "samples"] == [1.0, 2.0]


# ===========================================================================
# SLO burn rates (ISSUE-11)
# ===========================================================================

class TestSLOTracker:
    def _tracker(self, **kw):
        from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig, SLOTracker

        clock = {"now": 1000.0}
        cfg = SLOConfig(availability_target=0.99, latency_threshold_s=0.1,
                        latency_target=0.9, fast_window_s=10.0,
                        slow_window_s=100.0, **kw)
        return SLOTracker(cfg, clock=lambda: clock["now"]), clock

    def test_burn_rate_math(self):
        tracker, clock = self._tracker()
        # 100 requests, 2 failures → bad fraction 0.02 against a 0.01
        # budget → burn 2.0 on both windows
        for i in range(100):
            tracker.record(ok=i >= 2, latency_s=0.01)
        rates = tracker.burn_rates()
        assert rates["availability"]["fast"] == pytest.approx(2.0)
        assert rates["availability"]["slow"] == pytest.approx(2.0)
        assert not tracker.ok()

    def test_latency_objective_excludes_failures(self):
        tracker, clock = self._tracker()
        # 10 answered: 1 slow → bad 0.1 against budget 0.1 → burn 1.0;
        # the 5 failures must not dilute the latency denominator
        for _ in range(5):
            tracker.record(ok=False)
        for i in range(10):
            tracker.record(ok=True, latency_s=0.5 if i == 0 else 0.01)
        rates = tracker.burn_rates()
        assert rates["latency"]["fast"] == pytest.approx(1.0)
        # availability: 5/15 against 0.01 budget
        assert rates["availability"]["fast"] == pytest.approx(
            (5 / 15) / 0.01)

    def test_empty_window_is_nan_and_fails_closed(self):
        import math as _math

        tracker, clock = self._tracker()
        rates = tracker.burn_rates()
        assert _math.isnan(rates["availability"]["fast"])
        assert _math.isnan(rates["latency"]["slow"])
        # no data ≠ healthy: the admission signal fails closed
        assert tracker.ok() is False
        snap = tracker.snapshot()
        assert snap["ok"] is False
        # JSON surface: null, not NaN (healthz payload must stay JSON)
        assert snap["burn_rates"]["availability"]["fast"] is None
        assert json.loads(json.dumps(snap, allow_nan=False))["ok"] is False

    def test_backwards_clock_step_is_clamped_monotonic(self):
        # ISSUE-13 satellite: deployments inject wall clocks, and wall
        # clocks STEP (NTP slew, VM resume). A backwards step must not
        # skew window membership — event timestamps clamp to the
        # high-water mark, so the deque stays sorted and every window
        # evaluation sees a consistent "now"
        tracker, clock = self._tracker()
        for _ in range(10):
            tracker.record(ok=True, latency_s=0.01)
        clock["now"] = 920.0  # the wall clock steps BACK 80s
        for _ in range(10):
            tracker.record(ok=False)
        # all 20 events live at clamped t=1000: both windows see all of
        # them, and the failure fraction is exactly 10/20
        rates = tracker.burn_rates()
        assert rates["availability"]["fast"] == pytest.approx(
            (10 / 20) / 0.01)
        assert rates["availability"]["slow"] == pytest.approx(
            (10 / 20) / 0.01)
        assert tracker.snapshot()["totals"]["requests"] == 20
        # the deque is still sorted (the prune loop's contract)
        times = [t for t, _, _ in tracker._events]
        assert times == sorted(times)
        # when the clock recovers past the mark, real time resumes and
        # the fast window ages the burst out
        clock["now"] = 1015.0  # 15s past the clamp point, fast window 10s
        rates = tracker.burn_rates()
        import math as _math

        assert _math.isnan(rates["availability"]["fast"])  # aged out
        assert rates["availability"]["slow"] == pytest.approx(
            (10 / 20) / 0.01)

    def test_backwards_step_mid_stream_keeps_window_membership(self):
        # without the clamp, events recorded at the stepped-back time
        # land BEHIND newer events in the deque and the prune loop (which
        # stops at the first in-window timestamp) strands or drops them
        tracker, clock = self._tracker()
        tracker.record(ok=False)
        clock["now"] = 905.0  # back 95s: raw t would be outside slow-100
        tracker.record(ok=False)
        clock["now"] = 1000.0
        rates = tracker.burn_rates()
        # both events clamped to t=1000: both windows hold both failures
        assert rates["availability"]["fast"] == pytest.approx(100.0)
        assert tracker.ok() is False

    def test_multi_window_fast_burn_ages_out(self):
        tracker, clock = self._tracker()
        # a burst of failures, then a quiet fast-window: fast recovers,
        # slow still remembers — the multi-window property
        for _ in range(20):
            tracker.record(ok=False)
        clock["now"] += 50.0  # past fast (10s), inside slow (100s)
        for _ in range(20):
            tracker.record(ok=True, latency_s=0.01)
        rates = tracker.burn_rates()
        assert rates["availability"]["fast"] == pytest.approx(0.0)
        assert rates["availability"]["slow"] == pytest.approx(
            (20 / 40) / 0.01)
        assert not tracker.ok()  # slow window still burning

    def test_healthy_stream_is_ok(self):
        tracker, clock = self._tracker()
        for _ in range(50):
            tracker.record(ok=True, latency_s=0.01)
        assert tracker.ok() is True
        snap = tracker.snapshot()
        assert snap["ok"] is True
        assert snap["totals"] == {"requests": 50, "failed": 0, "slow": 0}

    def test_burn_gauges_exported(self):
        tracker, clock = self._tracker()
        for _ in range(10):
            tracker.record(ok=True, latency_s=0.01)
        tracker.snapshot()
        snap = get_registry().snapshot()
        series = {
            (s["labels"]["objective"], s["labels"]["window"]): s["value"]
            for s in snap["fleet_slo_burn_rate"]["series"]
        }
        assert series[("availability", "fast")] == 0.0
        assert len(series) == 4
        ok_series = snap["fleet_slo_ok"]["series"][0]
        assert ok_series["value"] == 1.0

    def test_events_prune_past_slow_window(self):
        tracker, clock = self._tracker()
        for _ in range(10):
            tracker.record(ok=False)
        clock["now"] += 200.0  # everything aged out of the slow window
        tracker.record(ok=True, latency_s=0.01)
        assert len(tracker._events) == 1
        rates = tracker.burn_rates()
        assert rates["availability"]["slow"] == pytest.approx(0.0)

    def test_config_validation(self):
        from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig

        with pytest.raises(ValueError):
            SLOConfig(availability_target=1.5).validate()
        with pytest.raises(ValueError):
            SLOConfig(fast_window_s=100.0, slow_window_s=10.0).validate()
        with pytest.raises(ValueError):
            SLOConfig(latency_threshold_s=0.0).validate()


# ===========================================================================
# trace_report: multi-trace merge + straggler attribution (ISSUE-11)
# ===========================================================================

class TestTraceReportFleet:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             *argv],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )

    @staticmethod
    def _trace(path, pid, spans):
        """Write a synthetic Chrome trace: spans = [(name, ts_us, dur_us,
        args), ...]."""
        events = [
            {"name": name, "ph": "X", "ts": ts, "dur": dur,
             "pid": pid, "tid": 1, "args": args}
            for name, ts, dur, args in spans
        ]
        with open(path, "w") as fh:
            json.dump({"traceEvents": events}, fh)
        return str(path)

    def test_multi_trace_merge_and_worker_tables(self, tmp_path):
        t0 = self._trace(tmp_path / "w0.json", 100, [
            ("resilience.step", 0.0, 1000.0, {"step": 0}),
            ("resilience.step", 2000.0, 1000.0, {"step": 1}),
        ])
        t1 = self._trace(tmp_path / "w1.json", 200, [
            ("resilience.step", 0.0, 3000.0, {"step": 0}),
            ("resilience.step", 3000.0, 3000.0, {"step": 1}),
        ])
        proc = self._run(t0, t1, "--json", str(tmp_path / "r.json"),
                         "--merge-out", str(tmp_path / "merged.json"))
        assert proc.returncode == 0, proc.stderr
        with open(tmp_path / "r.json") as fh:
            report = json.load(fh)
        assert report["pids"] == ["100", "200"]
        # per-pid occupancy: w1 did 3x the busy time
        assert report["workers"]["100"]["busy_s"] == pytest.approx(2e-3)
        assert report["workers"]["200"]["busy_s"] == pytest.approx(6e-3)
        # skew table names the imbalance on the shared span name
        skew = report["skew"]["resilience.step"]
        assert skew["skew"] == pytest.approx(3.0)
        assert "per-worker occupancy" in proc.stdout
        # the merged artifact is itself a foldable trace
        assert self._run(str(tmp_path / "merged.json")).returncode == 0

    def test_barrier_attribution_names_the_straggler(self, tmp_path):
        # worker 1 is the slow shard writer: long stage, no wait;
        # workers 0/2 stage fast and wait at the publication barrier
        spans = []
        for worker, pid, stage_us, wait_us in (
                (0, 100, 500.0, 4500.0),
                (1, 200, 5000.0, 100.0),
                (2, 300, 700.0, 4200.0)):
            spans.append((worker, pid, stage_us, wait_us))
        paths = []
        for worker, pid, stage_us, wait_us in spans:
            paths.append(self._trace(tmp_path / f"w{worker}.json", pid, [
                ("resilience.mesh_stage", 0.0, stage_us,
                 {"gen": 7, "worker": worker}),
                ("resilience.mesh_commit_wait", stage_us, wait_us,
                 {"gen": 7, "worker": worker}),
            ]))
        proc = self._run(*paths, "--json", str(tmp_path / "r.json"))
        assert proc.returncode == 0, proc.stderr
        with open(tmp_path / "r.json") as fh:
            report = json.load(fh)
        [barrier] = report["barriers"]
        assert barrier["generation"] == 7
        assert barrier["straggler"] == 1
        assert barrier["straggler_stage_s"] == pytest.approx(5e-3)
        assert barrier["peer_max_wait_s"] == pytest.approx(4.5e-3)
        assert "straggler worker 1" in proc.stdout

    def test_single_process_trace_has_no_worker_tables(self, tmp_path):
        t0 = self._trace(tmp_path / "one.json", 100, [
            ("serve.request", 0.0, 1000.0, {}),
        ])
        proc = self._run(t0, "--json", str(tmp_path / "r.json"))
        assert proc.returncode == 0, proc.stderr
        with open(tmp_path / "r.json") as fh:
            report = json.load(fh)
        assert "workers" not in report and "barriers" not in report

    def test_async_pairs_do_not_cross_processes(self, tmp_path):
        # same (name, id) b/e events on two pids: a merged trace must
        # pair within each pid, never across
        events = []
        for pid, t0, t1 in ((100, 0.0, 1000.0), (200, 500.0, 4500.0)):
            events.append({"name": "serve.flight", "ph": "b", "ts": t0,
                           "pid": pid, "tid": 1, "id": "f-1"})
            events.append({"name": "serve.flight", "ph": "e", "ts": t1,
                           "pid": pid, "tid": 1, "id": "f-1"})
        path = tmp_path / "pairs.json"
        path.write_text(json.dumps({"traceEvents": events}))
        proc = self._run(str(path), "--json", str(tmp_path / "r.json"))
        assert proc.returncode == 0, proc.stderr
        with open(tmp_path / "r.json") as fh:
            report = json.load(fh)
        assert report["spans"] == 2
        assert report["workers"]["100"]["busy_s"] == pytest.approx(1e-3)
        assert report["workers"]["200"]["busy_s"] == pytest.approx(4e-3)
