"""Unified telemetry plane tests (docs/OBSERVABILITY.md).

Covers the ISSUE-6 acceptance surface: trace-export schema validity (every
event carries ph/ts/pid/tid, spans nest, correlation ids survive the
batcher's two-stage pipeline), registry thread-safety under the batcher's
worker+completer threads, a Prometheus exposition golden test, the
overhead smoke (telemetry-off serve path allocates no registry series and
records no events; telemetry-on stays inside the 5%-of-wall budget on a
sleep-dominated fake engine), the serving surface (`generation` in
/healthz and /metrics, `?format=prom`, /debug/spans, /debug/trace device
captures, SIGUSR2), and the end-to-end drive: one Chrome trace showing a
supervisor training segment publishing a generation and a serving request
consuming it, with correlated spans across both planes.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.serving import (
    InferenceService,
    MicroBatcher,
    ServingEngine,
    make_server,
)
from gan_deeplearning4j_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
    percentiles,
)
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    Tracer,
    bind_trace_id,
    new_trace_id,
    unbind_trace_id,
)
from gan_deeplearning4j_tpu.utils import write_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES = 4, 6, 3


def _checkpoints(tmp_path):
    b = GraphBuilder(GraphConfig(seed=1))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    gen = b.build()
    b = GraphBuilder(GraphConfig(seed=2))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=5), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    cv = b.build()
    gen_path = str(tmp_path / "gen.zip")
    cv_path = str(tmp_path / "cv.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    write_model(cv_path, cv, cv.init(), save_updater=False)
    return gen_path, cv_path


# ===========================================================================
# one percentile definition across the repo
# ===========================================================================

class TestOneDefinition:
    def test_profiling_percentiles_is_the_registry_function(self):
        from gan_deeplearning4j_tpu.utils import profiling

        assert profiling.percentiles is percentiles

    def test_nearest_rank_contract_unchanged(self):
        # the PR 3 definition: nearest-rank over sorted samples
        assert percentiles([4.0, 1.0, 3.0, 2.0], (50,)) == {"p50": 2.0}
        assert percentiles([], (50,)) == {}
        out = percentiles(range(1, 101))
        assert out == {"p50": 50.0, "p95": 95.0, "p99": 99.0}


# ===========================================================================
# metrics registry
# ===========================================================================

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help", labelnames=("kind",))
        c.labels(kind="a").inc()
        c.labels(kind="a").inc(2)
        c.labels(kind="b").inc()
        assert c.labels(kind="a").value == 3
        assert c.labels(kind="b").value == 1
        g = reg.gauge("g")
        g.set(5)
        g.dec(2)
        assert g.labels().value == 3
        h = reg.histogram("h")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        child = h.labels()
        assert child.count == 3 and abs(child.total - 0.6) < 1e-9
        assert child.percentiles((50,)) == {"p50": 0.2}

    def test_reregistration_is_idempotent_conflict_raises(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", labelnames=("kind",))
        b = reg.counter("x_total", labelnames=("kind",))
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("x_total", labelnames=("other",))

    def test_counters_only_go_up(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("c_total").labels().inc(-1)

    def test_unknown_labels_rejected(self):
        reg = MetricsRegistry()
        fam = reg.counter("c_total", labelnames=("kind",))
        with pytest.raises(ValueError, match="expected labels"):
            fam.labels(wrong="x")

    def test_thread_safety_under_concurrent_writers(self):
        """The batcher updates series from its worker AND completer
        threads; `x += 1` interleaves at the bytecode level, so the series
        lock must make every increment land."""
        reg = MetricsRegistry()
        child = reg.counter("t_total", labelnames=("kind",)).labels(kind="x")
        hist = reg.histogram("t_seconds").labels()
        n, per = 8, 5000

        def work():
            for _ in range(per):
                child.inc()
                hist.observe(0.001)

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value == n * per
        assert hist.count == n * per

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "help", labelnames=("kind",)).labels(
            kind="a").inc()
        reg.histogram("h_seconds").observe(0.5)
        snap = reg.snapshot()
        assert snap["c_total"]["type"] == "counter"
        assert snap["c_total"]["series"] == [
            {"labels": {"kind": "a"}, "value": 1.0}]
        hrow = snap["h_seconds"]["series"][0]
        assert hrow["count"] == 1 and hrow["p50"] == 0.5


class TestPrometheus:
    def test_golden_exposition(self):
        reg = MetricsRegistry()
        reg.histogram("lat_seconds", "lat").observe(0.25)
        reg.gauge("queue_depth", "depth").set(2)
        reg.counter("requests_total", "reqs",
                    labelnames=("kind", "status")).labels(
            kind="sample", status="ok").inc(3)
        expected = (
            '# HELP lat_seconds lat\n'
            '# TYPE lat_seconds summary\n'
            'lat_seconds{quantile="0.5"} 0.25\n'
            'lat_seconds{quantile="0.95"} 0.25\n'
            'lat_seconds{quantile="0.99"} 0.25\n'
            'lat_seconds_sum 0.25\n'
            'lat_seconds_count 1\n'
            '# HELP queue_depth depth\n'
            '# TYPE queue_depth gauge\n'
            'queue_depth 2\n'
            '# HELP requests_total reqs\n'
            '# TYPE requests_total counter\n'
            'requests_total{kind="sample",status="ok"} 3\n'
        )
        assert reg.to_prometheus() == expected

    def test_label_values_escaped(self):
        reg = MetricsRegistry()
        reg.counter("e_total", labelnames=("why",)).labels(
            why='say "hi"\\\n').inc()
        text = reg.to_prometheus()
        assert r'why="say \"hi\"\\\n"' in text

    def test_metric_names_sanitized(self):
        reg = MetricsRegistry()
        reg.counter("bad-name.total").inc()
        assert "bad_name_total 1" in reg.to_prometheus()


# ===========================================================================
# span tracer
# ===========================================================================

class TestTracer:
    def test_span_event_schema(self):
        tr = Tracer(enabled=True)
        with tr.span("work", gen=7):
            time.sleep(0.002)
        (ev,) = tr.events()
        for field in ("name", "ph", "ts", "pid", "tid", "dur"):
            assert field in ev
        assert ev["ph"] == "X" and ev["name"] == "work"
        assert ev["pid"] == os.getpid()
        assert ev["dur"] >= 2000  # µs
        assert ev["args"]["gen"] == 7

    def test_spans_nest(self):
        tr = Tracer(enabled=True)
        with tr.span("outer"):
            time.sleep(0.001)
            with tr.span("inner"):
                time.sleep(0.001)
            time.sleep(0.001)
        by_name = {e["name"]: e for e in tr.events()}
        outer, inner = by_name["outer"], by_name["inner"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0

    def test_disabled_records_nothing_and_allocates_no_span(self):
        tr = Tracer()
        assert tr.span("a") is tr.span("b")  # the shared no-op object
        with tr.span("a"):
            pass
        tr.complete("x", 0.0, 1.0)
        tr.instant("y")
        tr.async_begin("z", "1")
        tr.async_end("z", "1")
        assert len(tr) == 0 and tr.events() == []

    def test_ring_buffer_bounds_memory(self):
        tr = Tracer(capacity=4, enabled=True)
        for i in range(10):
            tr.instant(f"e{i}")
        assert len(tr) == 4
        assert tr.dropped == 6
        assert [e["name"] for e in tr.events()] == ["e6", "e7", "e8", "e9"]

    def test_async_begin_end_pair(self):
        tr = Tracer(enabled=True)
        tr.async_begin("flight", "f-1", {"kind": "sample"})
        tr.async_end("flight", "f-1", {"status": "ok"})
        b, e = tr.events()
        assert (b["ph"], e["ph"]) == ("b", "e")
        assert b["id"] == e["id"] == "f-1"

    def test_contextvar_correlation_lands_in_args(self):
        tr = Tracer(enabled=True)
        token = bind_trace_id("req-42")
        try:
            tr.instant("hop")
        finally:
            unbind_trace_id(token)
        tr.instant("after")
        hop, after = tr.events()
        assert hop["args"]["trace_id"] == "req-42"
        assert "args" not in after or "trace_id" not in after.get("args", {})

    def test_dump_writes_loadable_chrome_trace(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("alpha"):
            pass
        path = tr.dump(str(tmp_path / "t.json"), {"source": "test"})
        with open(path) as fh:
            doc = json.load(fh)
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        assert doc["metadata"]["source"] == "test"
        assert doc["displayTimeUnit"] == "ms"

    def test_trace_ids_are_process_unique(self):
        a, b = new_trace_id(), new_trace_id()
        assert a != b and a.startswith(f"{os.getpid():x}-")


# ===========================================================================
# batcher pipeline: correlation survives worker+completer threads
# ===========================================================================

class TestBatcherTelemetry:
    def _drive(self, n=4):
        TRACER.enable()
        mb = MicroBatcher(run_fn=lambda k, r: r, max_batch=8,
                          max_latency=0.0)
        token = bind_trace_id("req-under-test")
        try:
            for _ in range(n):
                res = mb.submit("k", np.ones((1, 3), np.float32))
                assert res.ok
        finally:
            unbind_trace_id(token)
        mb.close()
        return mb, TRACER.events()

    def test_all_pipeline_stages_emit_spans(self):
        _, events = self._drive()
        names = {e["name"] for e in events}
        assert {"serve.batcher.submit", "serve.batcher.cut",
                "serve.batcher.dispatch", "serve.batcher.finalize",
                "serve.batcher.scatter", "serve.flight"} <= names

    def test_correlation_id_survives_both_thread_handoffs(self):
        """submit (caller thread) → cut/dispatch (worker thread) →
        finalize/scatter (completer thread): the id minted at submit must
        appear in every stage's args even though contextvars do not cross
        threads."""
        _, events = self._drive(n=1)
        by_name = {e["name"]: e for e in events}
        rid = by_name["serve.batcher.submit"]["args"]["trace_id"]
        assert rid == "req-under-test"
        assert rid in by_name["serve.batcher.cut"]["args"]["riders"]
        assert rid in by_name["serve.batcher.dispatch"]["args"]["riders"]
        assert rid in by_name["serve.batcher.scatter"]["args"]["riders"]
        # and the stages really ran on three distinct threads
        tids = {by_name[n]["tid"] for n in
                ("serve.batcher.submit", "serve.batcher.cut",
                 "serve.batcher.scatter")}
        assert len(tids) == 3

    def test_flight_async_pair_brackets_the_flush(self):
        _, events = self._drive(n=1)
        begins = [e for e in events
                  if e["name"] == "serve.flight" and e["ph"] == "b"]
        ends = [e for e in events
                if e["name"] == "serve.flight" and e["ph"] == "e"]
        assert len(begins) == len(ends) == 1
        assert begins[0]["id"] == ends[0]["id"]
        assert begins[0]["tid"] != ends[0]["tid"]  # worker vs completer

    def test_every_event_is_schema_valid(self):
        _, events = self._drive()
        for ev in events:
            for field in ("name", "ph", "ts", "pid", "tid"):
                assert field in ev, ev
            if ev["ph"] == "X":
                assert "dur" in ev

    def test_latency_percentiles_come_from_the_registry_histogram(self):
        mb, _ = self._drive(n=6)
        fam = get_registry().histogram(
            "serve_request_latency_seconds",
            labelnames=("kind",))
        child = fam.labels(kind="k")
        assert child.count == 6
        lat = mb.metrics()["latency_ms"]["k"]
        assert set(lat) == {"p50", "p95", "p99"}
        assert abs(lat["p50"] - child.percentiles((50,))["p50"] * 1e3) < 1e-9

    def test_registry_counters_mirror_the_ledger(self):
        mb, _ = self._drive(n=5)
        snap = get_registry().snapshot()
        ok_rows = [s for s in snap["serve_requests_total"]["series"]
                   if s["labels"] == {"kind": "k", "status": "ok"}]
        assert ok_rows and ok_rows[0]["value"] == 5
        assert snap["serve_flushes_total"]["series"][0]["value"] == \
            mb.metrics()["flushes"]
        assert "serve_stage_seconds" in snap

    def test_metrics_json_schema_is_preserved(self):
        mb, _ = self._drive()
        m = mb.metrics()
        for key in ("submitted", "completed", "shed_overloaded",
                    "shed_deadline", "errors", "flushes", "queue_depth",
                    "batch_occupancy", "latency_ms", "pipeline"):
            assert key in m
        assert m["submitted"] == {"k": 4} and m["completed"] == {"k": 4}


# ===========================================================================
# overhead smoke: off = nothing; on = inside the 5% budget
# ===========================================================================

class TestOverhead:
    def test_disabled_path_allocates_no_registry_series_or_events(self):
        mb = MicroBatcher(run_fn=lambda k, r: r, max_batch=8,
                          max_latency=0.0)
        # warm: the first request of a kind creates its series once
        assert mb.submit("k", np.ones((1, 3), np.float32)).ok
        reg = get_registry()
        baseline = reg.series_count()
        for _ in range(25):
            assert mb.submit("k", np.ones((1, 3), np.float32)).ok
        mb.close()
        assert reg.series_count() == baseline  # steady state: no new series
        assert len(TRACER) == 0  # tracing off: nothing recorded
        assert TRACER.span("a") is TRACER.span("b")  # no span objects either

    def test_enabled_overhead_within_budget_on_fake_engine(self):
        """Paired off/on rounds over a sleep-dominated fake engine (the
        pipelining tests' workload shape). Budget: telemetry-on within 5%
        of wall, with an absolute floor of 500 µs/request. Timing noise on
        a loaded CI box only ever ADDS time, so each estimate is the MIN
        of several alternating rounds, and a noisy attempt (where even the
        mins were perturbed) gets retried — the test proves an upper bound
        on overhead exists, and one clean measurement suffices for that;
        real per-request cost (a handful of dict/event appends, ~tens of
        µs) sits an order of magnitude under the gate."""
        n = 40
        rows = np.ones((1, 3), np.float32)

        def run_round(enabled):
            if enabled:
                TRACER.enable()
            else:
                TRACER.disable()
            mb = MicroBatcher(
                run_fn=lambda k, r: (time.sleep(0.002), r)[1],
                max_batch=8, max_latency=0.0)
            t0 = time.perf_counter()
            for _ in range(n):
                assert mb.submit("k", rows).ok
            elapsed = time.perf_counter() - t0
            mb.close()
            return elapsed

        on = off = per_request = 0.0
        for attempt in range(3):
            offs, ons = [], []
            for _ in range(3):
                offs.append(run_round(False))
                ons.append(run_round(True))
            TRACER.disable()
            off, on = min(offs), min(ons)
            per_request = (on - off) / n
            if on <= off * 1.05 or per_request < 500e-6:
                return
        assert on <= off * 1.05 or per_request < 500e-6, (
            f"telemetry-on {on:.4f}s vs off {off:.4f}s "
            f"({per_request * 1e6:.0f}µs/request over budget in all "
            f"attempts)")


# ===========================================================================
# serving surface: generation, prom exposition, debug hooks
# ===========================================================================

class TestServingSurface:
    def _service(self, tmp_path, generation=None, **kw):
        gen_path, cv_path = _checkpoints(tmp_path)
        engine = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path, buckets=(1, 8),
            feature_vertex="feat_1", generation=generation,
        )
        return InferenceService(engine, warmup=False, max_latency=0.0, **kw)

    def test_generation_surfaces_in_healthz_and_metrics(self, tmp_path):
        svc = self._service(tmp_path, generation=7)
        try:
            assert svc.healthz()["generation"] == 7
            m = svc.metrics()
            assert m["generation"] == 7
            assert m["engine"]["generation"] == 7
            snap = get_registry().snapshot()
            assert snap["serving_generation"]["series"][0]["value"] == 7
        finally:
            svc.close()

    def test_unversioned_engine_reports_generation_none(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            assert svc.healthz()["generation"] is None
            assert svc.metrics()["generation"] is None
        finally:
            svc.close()

    def test_prometheus_exposition_over_http(self, tmp_path):
        import urllib.request

        svc = self._service(tmp_path, generation=3)
        server = make_server(svc, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?format=prom",
                    timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "# TYPE serve_queue_depth gauge" in text
            assert "serving_generation 3" in text
            # the JSON payload still answers without the format knob
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                body = json.loads(r.read())
            assert body["generation"] == 3
        finally:
            server.shutdown()
            server.server_close()
            svc.close()

    def test_debug_spans_exports_chrome_trace(self, tmp_path):
        TRACER.enable()
        svc = self._service(tmp_path)
        try:
            TRACER.instant("marker")
            status, body = svc.handle("GET", "/debug/spans")
            assert status == 200
            assert any(e["name"] == "marker" for e in body["traceEvents"])
        finally:
            svc.close()

    def test_debug_trace_captures_device_profile(self, tmp_path):
        """Async by default: 202 answers immediately with the path the
        artifact WILL land at (cold profiler start/stop can take tens of
        seconds — no HTTP client should wait through that)."""
        artifacts = str(tmp_path / "captures")
        svc = self._service(tmp_path, artifacts_dir=artifacts)
        try:
            status, body = svc.handle("POST", "/debug/trace?ms=40")
            assert status == 202, body
            out = body["artifact"]
            assert out.startswith(artifacts)
            deadline = time.monotonic() + 120.0
            captured = []
            while time.monotonic() < deadline and not captured:
                captured = [
                    os.path.join(root, f)
                    for root, _, files in os.walk(out) for f in files
                ]
                time.sleep(0.05)
            assert captured, "capture produced no profiler artifacts"
        finally:
            svc.close()

    def test_debug_trace_block_mode_waits_for_the_artifact(self, tmp_path):
        artifacts = str(tmp_path / "captures_block")
        svc = self._service(tmp_path, artifacts_dir=artifacts)
        try:
            status, body = svc.handle("POST", "/debug/trace?ms=30&block=1")
            assert status == 200, body
            out = body["artifact"]
            assert os.path.isdir(out)
            assert any(files for _, _, files in os.walk(out))
        finally:
            svc.close()

    def test_debug_trace_rejects_bad_duration(self, tmp_path):
        svc = self._service(tmp_path, artifacts_dir=str(tmp_path / "c"))
        try:
            assert svc.handle("POST", "/debug/trace?ms=nope")[0] == 400
            assert svc.handle("POST", "/debug/trace?ms=0")[0] == 400
            assert svc.handle("POST", "/debug/trace?ms=999999")[0] == 400
        finally:
            svc.close()


class TestSignalCapture:
    def test_sigusr2_triggers_background_capture(self, tmp_path):
        from gan_deeplearning4j_tpu.telemetry.device import (
            install_signal_capture,
        )

        artifacts = str(tmp_path / "sig")
        old = signal.getsignal(signal.SIGUSR2)
        try:
            install_signal_capture(artifacts, duration_ms=30)
            os.kill(os.getpid(), signal.SIGUSR2)
            deadline = time.monotonic() + 10.0
            files = []
            while time.monotonic() < deadline and not files:
                files = [
                    os.path.join(root, f)
                    for root, _, fs in os.walk(artifacts) for f in fs
                ]
                time.sleep(0.05)
            assert files, "SIGUSR2 produced no capture artifacts"
        finally:
            signal.signal(signal.SIGUSR2, old)


# ===========================================================================
# trace_report: the campaign gate
# ===========================================================================

class TestTraceReport:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             *argv],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )

    def test_folds_a_real_trace(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("serve.batcher.dispatch", kind="sample"):
            time.sleep(0.002)
        with tr.span("serve.batcher.finalize", kind="sample"):
            time.sleep(0.001)
        path = tr.dump(str(tmp_path / "trace.json"))
        proc = self._run(path, "--json", str(tmp_path / "report.json"))
        assert proc.returncode == 0, proc.stderr
        assert "serve.batcher.dispatch" in proc.stdout
        with open(tmp_path / "report.json") as fh:
            report = json.load(fh)
        assert report["spans"] == 2
        assert report["phases"]["serve.batcher.dispatch"]["count"] == 1

    def test_empty_trace_fails_the_gate(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text('{"traceEvents": []}\n')
        proc = self._run(str(path))
        assert proc.returncode == 1
        assert "no complete spans" in proc.stderr

    def test_malformed_trace_fails_the_gate(self, tmp_path):
        path = tmp_path / "broken.json"
        path.write_text("{not json\n")
        assert self._run(str(path)).returncode == 1
        path2 = tmp_path / "schema.json"
        path2.write_text(json.dumps(
            {"traceEvents": [{"name": "x", "ph": "X", "ts": 1.0}]}))
        proc = self._run(str(path2))
        assert proc.returncode == 1
        assert "missing" in proc.stderr

    def test_missing_file_fails_the_gate(self, tmp_path):
        assert self._run(str(tmp_path / "nope.json")).returncode == 1


# ===========================================================================
# the end-to-end drive: train → publish generation → serve it, one trace
# ===========================================================================

class TestEndToEndTrace:
    def test_supervisor_publish_and_serving_consume_share_one_trace(
            self, tmp_path):
        """ISSUE-6 acceptance: a supervisor training segment publishes a
        generation, a serving engine loads that generation and answers a
        request, and ONE Chrome trace holds correlated spans from both
        planes (the publish span and the serving plane agree on the
        generation number; the request's correlation id crosses the
        batcher pipeline)."""
        from gan_deeplearning4j_tpu.harness import (
            ExperimentConfig,
            GanExperiment,
        )
        from gan_deeplearning4j_tpu.resilience import (
            SupervisorConfig,
            TrainingSupervisor,
        )

        TRACER.enable()
        cfg = ExperimentConfig(
            model_family="tabular", num_features=16, z_size=4,
            batch_size_train=8, batch_size_pred=8,
            height=1, width=1, channels=1,
            save_models=False,
            output_dir=os.path.join(str(tmp_path), "out"),
        )
        rng = np.random.default_rng(0)
        feats = rng.random((16, 16), dtype=np.float32)
        labels = np.eye(10, dtype=np.float32)[np.arange(16) % 10]

        sup = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=2, publish_every=2),
            feats, labels,
            store_root=os.path.join(str(tmp_path), "store"))
        summary = sup.run()
        assert summary["status"] == "completed"

        # restore the trained state and publish a SERVING bundle as the
        # next store generation — the artifact a live server would poll
        exp = GanExperiment(cfg)
        exp.load_models(directory=sup.store.latest_valid().path)
        published = exp.publish_for_serving(store=sup.store)
        serving_gen = published["generation"]
        assert serving_gen is not None

        engine = ServingEngine.from_bundle(
            published["directory"], buckets=(1, 4))
        service = InferenceService(engine, warmup="sync", max_latency=0.0)
        try:
            assert service.healthz()["generation"] == serving_gen
            status, body = service.handle(
                "POST", "/v1/sample",
                {"data": (rng.random((1, 4)) * 2 - 1).tolist()})
            assert status == 200 and body["status"] == "ok"
        finally:
            service.close()

        trace = TRACER.chrome_trace({"drive": "e2e"})
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        # both planes in one trace
        assert {"resilience.step", "resilience.segment",
                "resilience.publish"} <= names
        assert {"serve.engine.restore", "serve.request",
                "serve.batcher.dispatch", "serve.batcher.scatter"} <= names
        # correlated: the serving bundle's publish span carries the SAME
        # generation number the serving plane reports
        publish_gens = {e["args"]["gen"] for e in events
                        if e["name"] == "resilience.publish"}
        assert serving_gen in publish_gens
        restore = next(e for e in events
                       if e["name"] == "serve.engine.restore")
        assert restore["args"]["generation"] == serving_gen
        # and the HTTP request's correlation id crossed the pipeline
        request = next(e for e in events if e["name"] == "serve.request")
        rid = request["args"]["trace_id"]
        scatter = next(e for e in events
                       if e["name"] == "serve.batcher.scatter")
        assert rid in scatter["args"]["riders"]

        # the trace is a valid, foldable artifact — the campaign gate
        path = str(tmp_path / "e2e_trace.json")
        TRACER.dump(path)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"), path],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "resilience.publish" in proc.stdout
