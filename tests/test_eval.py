"""Eval layer tests: accuracy (cell-6 analog), manifold PNG, FID harness."""

import numpy as np
import pytest

from gan_deeplearning4j_tpu.eval import (
    FeatureStats,
    accuracy_from_csvs,
    accuracy_score,
    evaluate_classifier,
    fid_from_stats,
    fid_score,
    graph_feature_fn,
    render_manifold,
    tile_images,
    write_png,
)


class TestAccuracy:
    def test_known_values(self):
        probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4], [0.3, 0.7]])
        assert accuracy_score(probs, np.array([0, 1, 1, 1])) == 0.75
        one_hot = np.eye(2)[[0, 1, 1, 1]]
        assert accuracy_score(probs, one_hot) == 0.75

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            accuracy_score(np.zeros((3, 2)), np.zeros(4))

    def test_csv_flow(self, tmp_path):
        # 4 features per row + label column, 3 rows; predictions argmax
        # matches labels on 2 of 3
        test_csv = tmp_path / "t.csv"
        rows = np.hstack([np.random.rand(3, 4), np.array([[0.0], [1.0], [2.0]])])
        np.savetxt(test_csv, rows, delimiter=",")
        preds = np.array([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1], [0.7, 0.2, 0.1]])
        pred_csv = tmp_path / "p.csv"
        np.savetxt(pred_csv, preds, delimiter=",")
        acc = accuracy_from_csvs(str(pred_csv), str(test_csv), num_features=4)
        assert abs(acc - 2.0 / 3.0) < 1e-9

    def test_evaluate_classifier_on_graph(self):
        from gan_deeplearning4j_tpu.nn import (
            DenseLayer,
            GraphBuilder,
            GraphConfig,
            InputType,
            OutputLayer,
        )

        b = GraphBuilder(GraphConfig(seed=0))
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(8))
        b.add_layer("h", DenseLayer(n_out=16), "in")
        b.add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "h")
        b.set_outputs("out")
        g = b.build()
        params = g.init()
        x = np.random.default_rng(0).random((10, 8), dtype=np.float32)
        y = np.random.default_rng(1).integers(0, 3, 10)
        acc = evaluate_classifier(g, params, x, y, batch_size=4)
        assert 0.0 <= acc <= 1.0


class TestImages:
    def test_tile_layout(self):
        imgs = np.arange(4 * 2 * 2, dtype=np.float32).reshape(4, 2, 2)
        mosaic = tile_images(imgs, 2)
        assert mosaic.shape == (4, 4)
        # row-major placement: image 1 occupies top-right block
        np.testing.assert_array_equal(mosaic[0:2, 2:4], imgs[1])

    def test_tile_wrong_count(self):
        with pytest.raises(ValueError):
            tile_images(np.zeros((3, 2, 2)), 2)

    def test_png_signature_and_roundtrip_sizes(self, tmp_path):
        path = str(tmp_path / "g.png")
        write_png(path, np.random.rand(7, 5))
        data = open(path, "rb").read()
        assert data[:8] == b"\x89PNG\r\n\x1a\n"
        assert b"IHDR" in data and b"IDAT" in data and b"IEND" in data
        # RGB path
        write_png(str(tmp_path / "c.png"), np.random.rand(4, 4, 3))
        # bad shape
        with pytest.raises(ValueError):
            write_png(str(tmp_path / "bad.png"), np.zeros((2, 2, 4)))

    def test_render_manifold_from_csv(self, tmp_path):
        flat = np.random.rand(100, 784)
        csv = tmp_path / "mnist_out_1.csv"
        np.savetxt(csv, flat, delimiter=",")
        out = render_manifold(str(csv), str(tmp_path / "m.png"), grid=10, side=28)
        assert open(out, "rb").read()[:8] == b"\x89PNG\r\n\x1a\n"


class TestFid:
    def test_identical_distributions_near_zero(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(500, 8))
        fid = fid_from_stats(
            FeatureStats.from_features(x), FeatureStats.from_features(x)
        )
        # not exactly 0: the eps regularizer leaves a ~1e-5 residual
        assert abs(fid) < 1e-3

    def test_mean_shift_matches_closed_form(self):
        # same covariance, shifted mean: FID ≈ ||Δμ||²
        rng = np.random.default_rng(1)
        x = rng.normal(size=(20000, 4))
        shift = np.array([1.0, -2.0, 0.5, 0.0])
        fid = fid_score(x, x + shift)
        assert abs(fid - float(shift @ shift)) < 0.05 * float(shift @ shift) + 0.05

    def test_orders_models(self):
        # a wildly off distribution must score worse than a close one
        rng = np.random.default_rng(2)
        real = rng.normal(size=(1000, 6))
        close = real + 0.1 * rng.normal(size=real.shape)
        far = 5.0 + 3.0 * rng.normal(size=real.shape)
        assert fid_score(real, close) < fid_score(real, far)

    def test_too_few_samples_raises(self):
        with pytest.raises(ValueError):
            FeatureStats.from_features(np.zeros((1, 3)))

    def test_frozen_feature_fn_pinned(self):
        """The stable extractor's feature space must NEVER move between
        runs/rounds (round-2 VERDICT weak #4): pin exact values for a fixed
        input. If this test fails, every historical FID number in
        BASELINE.md/artifacts becomes incomparable — bump the seed and
        re-score rather than silently changing the stack.

        Pin provenance: captured on the jax 0.4.37 wheel this container
        ships (threefry PRNG + HIGHEST-precision conv; the extractor is
        platform-stable at rtol 2e-4 by construction, see frozen_feature_fn).
        The feature space is a function of the installed jax PRNG/conv stack:
        a wheel upgrade that moves these values is a feature-space EPOCH
        change — re-pin here AND re-score every stored FID in
        BASELINE.md/artifacts in the same PR, never widen the tolerance to
        paper over it. The tolerance below (rtol 2e-4, atol 2e-5) is the
        documented cross-platform envelope, not a drift allowance."""
        from gan_deeplearning4j_tpu.eval.fid import frozen_feature_fn

        fn = frozen_feature_fn(28, 28, 1, seed=666)
        x = np.linspace(0, 1, 4 * 784, dtype=np.float32).reshape(4, 784)
        feats = fn(x)
        assert feats.shape == (4, 224)
        np.testing.assert_allclose(
            feats[0, :4],
            [-0.262800, -0.141369, -0.274840, -0.115256],
            rtol=2e-4, atol=2e-5,
        )
        np.testing.assert_allclose(
            feats[2, -4:],
            [0.023386, -0.036663, -0.009465, 0.024517],
            rtol=2e-4, atol=2e-5,
        )
        # independent of anything trained: a second instantiation is
        # bit-identical
        assert np.array_equal(feats, frozen_feature_fn(28, 28, 1, seed=666)(x))

    def test_frozen_feature_forward_matches_extract(self):
        """``extract.forward`` (the raw jittable composition hook the
        quality-run tracker fuses with the generator) must produce the same
        features as the batched host-side ``extract``."""
        import jax
        import jax.numpy as jnp

        from gan_deeplearning4j_tpu.eval.fid import frozen_feature_fn

        fn = frozen_feature_fn(28, 28, 1, seed=666, batch_size=3)
        x = np.linspace(0, 1, 8 * 784, dtype=np.float32).reshape(8, 784)
        via_forward = np.asarray(jax.jit(fn.forward)(jnp.asarray(x)))
        np.testing.assert_allclose(via_forward, fn(x), rtol=1e-6, atol=1e-7)
        # image-shaped input goes through the same reshape path
        np.testing.assert_allclose(
            np.asarray(jax.jit(fn.forward)(jnp.asarray(x.reshape(8, 28, 28, 1)))),
            via_forward, rtol=1e-6, atol=1e-7,
        )

    def test_frozen_feature_fn_orders_models(self):
        from gan_deeplearning4j_tpu.eval.fid import frozen_feature_fn

        fn = frozen_feature_fn(8, 8, 1, seed=1)
        rng = np.random.default_rng(5)
        real = rng.random((256, 64), dtype=np.float32)
        close = np.clip(real + 0.05 * rng.standard_normal(real.shape), 0, 1).astype(
            np.float32
        )
        far = np.zeros_like(real)
        assert fid_score(real, close, fn) < fid_score(real, far, fn)

    def test_graph_feature_fn_on_discriminator(self):
        from gan_deeplearning4j_tpu.models import dcgan_mnist

        dis = dcgan_mnist.build_discriminator()
        params = dis.init()
        extract = graph_feature_fn(dis, params, "dis_dense_layer_6", batch_size=8)
        feats = extract(np.random.default_rng(0).random((12, 784), dtype=np.float32))
        assert feats.shape == (12, 1024)
        rng = np.random.default_rng(3)
        real = rng.random((32, 784), dtype=np.float32)
        fake = rng.random((32, 784), dtype=np.float32)
        fid = fid_score(real, fake, feature_fn=extract)
        assert np.isfinite(fid) and fid >= 0.0


class TestInceptionHook:
    """inception_feature_fn (round-4 VERDICT item 7): user-supplied weights
    via $INCEPTION_WEIGHTS, frozen-extractor fallback, branched topology."""

    @staticmethod
    def _tiny_weights(path):
        """A tiny branched feature net in the documented npz schema: conv →
        {1x1 branch, maxpool branch} → concat → global_avgpool (the minimal
        shape of an Inception block)."""
        import json

        rng = np.random.default_rng(0)
        schema = {
            "input": {"height": 16, "width": 16, "channels": 3,
                      "mean": [0.5, 0.5, 0.5], "std": [0.5, 0.5, 0.5]},
            "nodes": [
                {"name": "c1", "op": "conv", "in": "input", "stride": 2,
                 "padding": "SAME", "activation": "relu",
                 "kernel": "c1/kernel", "bias": "c1/bias"},
                {"name": "b1", "op": "conv", "in": "c1", "stride": 1,
                 "padding": "SAME", "activation": "relu",
                 "kernel": "b1/kernel"},
                {"name": "b2", "op": "maxpool", "in": "c1", "size": 3,
                 "stride": 1, "padding": "SAME"},
                {"name": "cat", "op": "concat", "in": ["b1", "b2"]},
                {"name": "feat", "op": "global_avgpool", "in": "cat"},
            ],
            "output": "feat",
        }
        np.savez(
            path,
            __schema__=json.dumps(schema),
            **{
                "c1/kernel": rng.normal(size=(3, 3, 3, 4)).astype(np.float32) * 0.2,
                "c1/bias": rng.normal(size=(4,)).astype(np.float32) * 0.1,
                "b1/kernel": rng.normal(size=(1, 1, 4, 6)).astype(np.float32) * 0.2,
            },
        )

    def test_loads_weights_and_scores(self, tmp_path):
        from gan_deeplearning4j_tpu.eval import inception_feature_fn

        wpath = str(tmp_path / "tiny_inception.npz")
        self._tiny_weights(wpath)
        extract = inception_feature_fn(8, 8, 1, path=wpath, batch_size=8)
        assert extract.source == f"inception:{wpath}"
        rng = np.random.default_rng(1)
        x = rng.random((12, 64), dtype=np.float32)
        feats = extract(x)
        assert feats.shape == (12, 10)  # 6 conv + 4 pool channels
        assert np.isfinite(feats).all()
        # deterministic, and grayscale input broadcast + resize engaged
        np.testing.assert_array_equal(extract(x), feats)
        fid = fid_score(
            rng.random((32, 64), dtype=np.float32),
            rng.random((32, 64), dtype=np.float32),
            feature_fn=extract,
        )
        assert np.isfinite(fid) and fid >= 0.0

    def test_avgpool_excludes_padding_from_divisor(self, tmp_path):
        """SAME-padded avgpool must divide by the REAL window element count
        (TF / pytorch-fid count_include_pad=False): a constant input then
        pools to exactly that constant everywhere — a /k² divisor would
        understate the edges and break literature comparability."""
        import json

        from gan_deeplearning4j_tpu.eval import inception_feature_fn

        schema = {
            "input": {"height": 6, "width": 6, "channels": 1},
            "nodes": [
                {"name": "p", "op": "avgpool", "in": "input", "size": 3,
                 "stride": 1, "padding": "SAME"},
                {"name": "f", "op": "global_avgpool", "in": "p"},
            ],
            "output": "f",
        }
        wpath = str(tmp_path / "avg.npz")
        np.savez(wpath, __schema__=json.dumps(schema))
        extract = inception_feature_fn(6, 6, 1, path=wpath, batch_size=4)
        x = np.full((3, 36), 0.625, dtype=np.float32)
        feats = extract(x)
        np.testing.assert_allclose(feats, 0.625, rtol=1e-6)

    def test_env_var_and_fallback(self, tmp_path, monkeypatch):
        from gan_deeplearning4j_tpu.eval import inception_feature_fn

        # no path, no env: frozen fallback with the same call contract
        monkeypatch.delenv("INCEPTION_WEIGHTS", raising=False)
        fb = inception_feature_fn(8, 8, 1, batch_size=8)
        assert fb.source == "frozen"
        assert fb(np.random.default_rng(2).random((4, 64), dtype=np.float32)).shape \
            == (4, 224)
        # env-supplied path wins
        wpath = str(tmp_path / "w.npz")
        self._tiny_weights(wpath)
        monkeypatch.setenv("INCEPTION_WEIGHTS", wpath)
        ext = inception_feature_fn(8, 8, 1, batch_size=8)
        assert ext.source == f"inception:{wpath}"
