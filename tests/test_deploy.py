"""deploy/ subsystem tests: bundle watcher (store + directory modes,
corrupt-generation skip), the batcher's zero-downtime engine-swap seam,
the canary quality gate (with the importable quality_run probe), the
reload controller end-to-end against real engines, the supervisor's
serve-publish cadence, and the subprocess reload drill (slow).

Engine-facing tests use the same tiny dense graphs as tests/test_serving
(millisecond compiles) — the reload plane is model-agnostic.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gan_deeplearning4j_tpu.deploy import (
    BundleCandidate,
    CanaryGate,
    CanaryThresholds,
    ReloadBusy,
    ReloadController,
    StoreWatcher,
    load_quality_probe,
)
from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.resilience import (
    CheckpointStore,
    SupervisorConfig,
    TrainingSupervisor,
    corrupt_generation,
)
from gan_deeplearning4j_tpu.serving import InferenceService, MicroBatcher, ServingEngine
from gan_deeplearning4j_tpu.utils import write_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES, HIDDEN = 4, 6, 3, 5


def tiny_generator(seed=1):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    return b.build()


def tiny_classifier(seed=2):
    b = GraphBuilder(GraphConfig(seed=seed))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=HIDDEN), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    return b.build()


def write_bundle(directory, *, gen_seed=1, generation=None, step=0,
                 poison=False):
    """A serving bundle (gen + cv zips + serving.json) in ``directory``."""
    os.makedirs(directory, exist_ok=True)
    gen, cv = tiny_generator(seed=gen_seed), tiny_classifier()
    gen_params = gen.init()
    if poison:
        import jax

        gen_params = jax.tree_util.tree_map(
            lambda a: np.full_like(np.asarray(a), 25.0), gen_params)
    write_model(os.path.join(directory, "gen.zip"), gen, gen_params,
                save_updater=False)
    write_model(os.path.join(directory, "cv.zip"), cv, cv.init(),
                save_updater=False)
    manifest = {
        "format_version": 1,
        "generator": "gen.zip",
        "classifier": "cv.zip",
        "feature_vertex": "feat_1",
        "generation": generation,
        "step": step,
    }
    with open(os.path.join(directory, "serving.json"), "w") as fh:
        json.dump(manifest, fh)
    return manifest


def publish_bundle(store, *, gen_seed=1, step=0, poison=False):
    """Publish a serving bundle as a digest-verified store generation."""
    number = store.next_number()
    gen = store.publish(
        lambda d: write_bundle(d, gen_seed=gen_seed, generation=number,
                               step=step, poison=poison),
        step=step, extra={"kind": "serving"},
    )
    assert gen.number == number
    return gen


def publish_training(store, *, step=0):
    """A training-checkpoint generation (no serving.json) — the thing a
    serving watcher must skip without quarantining."""
    def writer(d):
        with open(os.path.join(d, "tabular_dis_model.zip"), "wb") as fh:
            fh.write(b"weights " * 16)

    return store.publish(writer, step=step, extra={"kind": "training"})


# ===========================================================================
# watcher
# ===========================================================================

class TestStoreWatcher:
    def test_finds_newest_valid_serving_generation(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        publish_bundle(store, gen_seed=1)
        g1 = publish_bundle(store, gen_seed=2, step=5)
        cand = StoreWatcher(store=store).poll_once()
        assert cand.generation == g1.number
        assert cand.path == g1.path

    def test_nothing_newer_than_current(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        g0 = publish_bundle(store)
        w = StoreWatcher(store=store)
        assert w.poll_once(current_generation=g0.number) is None
        # and an empty store offers nothing at all
        assert StoreWatcher(
            store=CheckpointStore(str(tmp_path / "empty"))).poll_once() is None

    def test_training_generations_skipped_not_quarantined(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        g0 = publish_bundle(store)
        t1 = publish_training(store, step=9)
        w = StoreWatcher(store=store)
        # the newest generation is a training checkpoint: not servable,
        # but also not corrupt — skipped silently, nothing offered
        assert w.poll_once(current_generation=g0.number) is None
        assert store.entry(t1.number).get("status") == "published"

    def test_corrupt_newer_generation_quarantined_and_skipped(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        g1 = publish_bundle(store, gen_seed=2)
        corrupt_generation(store, g1.number, seed=3)
        cand = StoreWatcher(store=store).poll_once()
        # the walk fell back to the intact generation…
        assert cand.generation == g0.number
        # …and the corrupt one went through the store's quarantine
        assert store.entry(g1.number).get("status") == "quarantined"
        assert g1.number in store.quarantined()

    def test_discard_with_quarantine_is_permanent(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        g1 = publish_bundle(store, gen_seed=2)
        w = StoreWatcher(store=store)
        cand = w.poll_once()
        assert cand.generation == g1.number
        w.discard(cand, "canary: fid blew up", quarantine=True)
        assert store.entry(g1.number).get("status") == "quarantined"
        # the walk now offers the previous generation, and a FRESH watcher
        # (a restarted server) can't see the quarantined one either
        assert w.poll_once().generation == g0.number
        assert StoreWatcher(store=store).poll_once().generation == g0.number

    def test_discard_without_quarantine_only_skips_locally(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=10)
        publish_bundle(store, gen_seed=1)
        g1 = publish_bundle(store, gen_seed=2)
        w = StoreWatcher(store=store)
        cand = w.poll_once()
        w.discard(cand, "kind mismatch", quarantine=False)
        assert w.poll_once().generation == g1.number - 1
        assert store.entry(g1.number).get("status") == "published"

    def test_directory_mode_tracks_manifest_content(self, tmp_path):
        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, step=1)
        w = StoreWatcher(path=bundle)
        cand = w.poll_once()
        assert cand is not None and cand.path == bundle
        assert cand.token == StoreWatcher.dir_token(bundle)
        # same content -> nothing new; changed manifest -> new candidate
        assert w.poll_once(current_token=cand.token) is None
        write_bundle(bundle, step=2)
        newer = w.poll_once(current_token=cand.token)
        assert newer is not None and newer.token != cand.token

    def test_exactly_one_source_required(self, tmp_path):
        with pytest.raises(ValueError):
            StoreWatcher()
        with pytest.raises(ValueError):
            StoreWatcher(store=CheckpointStore(str(tmp_path)),
                         path=str(tmp_path))


# ===========================================================================
# batcher engine-swap seam
# ===========================================================================

class _SwapFake:
    """dispatch/finalize fake whose results are stamped with the engine's
    tag — so every ServeResult proves which engine served it — and which
    asserts it never finalizes another engine's handle."""

    def __init__(self, tag, finalize_s=0.0):
        self.tag = float(tag)
        self.finalize_s = finalize_s
        self.dispatched = threading.Event()

    def dispatch(self, kind, rows_list):
        self.dispatched.set()
        return (self, [np.asarray(r) for r in rows_list])

    def finalize(self, handle):
        owner, rows_list = handle
        assert owner is self, "flight finalized on a foreign engine"
        if self.finalize_s:
            time.sleep(self.finalize_s)
        rows = (rows_list[0] if len(rows_list) == 1
                else np.concatenate(rows_list))
        return np.full((rows.shape[0], 2), self.tag, np.float32)


class TestBatcherSwap:
    def test_inflight_finalizes_on_old_engine_new_flushes_on_new(self):
        # the satellite's scenario: a slow flight is IN the device when the
        # swap lands — it must finalize on the old engine while the next
        # flush dispatches on the new one
        old, new = _SwapFake(1, finalize_s=0.3), _SwapFake(2)
        mb = MicroBatcher(engine=old, max_latency=0.0, pipeline_depth=2)
        first = {}

        def client():
            first["r"] = mb.submit("k", np.zeros((1, 3), np.float32),
                                   timeout=10.0)

        t = threading.Thread(target=client)
        t.start()
        assert old.dispatched.wait(5.0)  # the flight is in the air
        assert mb.swap_engine(new) is old
        second = mb.submit("k", np.zeros((1, 3), np.float32), timeout=10.0)
        t.join(10.0)
        assert first["r"].ok and first["r"].data[0, 0] == 1.0
        assert second.ok and second.data[0, 0] == 2.0
        # retirement condition: the old engine's last flight has drained
        assert mb.flights_on(old) == 0 and mb.flights_on(new) == 0
        assert mb.engine is new
        mb.close()

    def test_zero_shed_invariant_across_three_swaps_under_load(self):
        # sustained concurrent load across 3 consecutive swaps: every
        # request must come back ok — nothing shed, nothing lost, nothing
        # errored by the swaps
        engines = [_SwapFake(i, finalize_s=0.002) for i in range(4)]
        mb = MicroBatcher(engine=engines[0], max_latency=0.0,
                          max_queue=512, pipeline_depth=2)
        results, stop = [], threading.Event()
        lock = threading.Lock()

        def client(tid):
            rng = np.random.default_rng(tid)
            while not stop.is_set():
                r = mb.submit("k", np.zeros(
                    (int(rng.integers(1, 4)), 3), np.float32), timeout=30.0)
                with lock:
                    results.append(r)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for nxt in engines[1:]:
            time.sleep(0.15)
            mb.swap_engine(nxt)
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(30.0)
        # one more request after the dust settles: served by the FINAL engine
        last = mb.submit("k", np.zeros((1, 3), np.float32), timeout=10.0)
        metrics = mb.metrics()
        mb.close()
        assert len(results) > 20  # the load was real
        assert all(r.ok for r in results), [
            (r.status, r.error) for r in results if not r.ok][:5]
        served = {r.data[0, 0] for r in results}
        assert served <= {0.0, 1.0, 2.0, 3.0}
        assert last.ok and last.data[0, 0] == 3.0
        assert metrics["engine_swaps"] == 3
        assert metrics["shed_overloaded"] == 0
        assert metrics["shed_deadline"] == 0
        assert metrics["errors"] == 0
        for old in engines[:3]:
            assert mb.flights_on(old) == 0

    def test_swap_requires_engine_mode(self):
        mb = MicroBatcher(run_fn=lambda kind, rows: rows)
        with pytest.raises(ValueError, match="engine-mode"):
            mb.swap_engine(_SwapFake(9))
        assert mb.engine is None
        mb.close()

    def test_swap_to_none_rejected(self):
        mb = MicroBatcher(engine=_SwapFake(0))
        with pytest.raises(ValueError):
            mb.swap_engine(None)
        mb.close()


# ===========================================================================
# quality probe (the factored scripts/quality_run.py function)
# ===========================================================================

class TestQualityProbe:
    def test_importable_and_deterministic(self):
        probe = load_quality_probe()
        real = np.random.default_rng(0).random((64, FEAT), np.float32)

        def sample_fn(z):
            return np.tile(np.tanh(z.sum(axis=1, keepdims=True)), (1, FEAT))

        a = probe(sample_fn, real, z_size=Z, num_samples=32)
        b = probe(sample_fn, real, z_size=Z, num_samples=32)
        assert a == b
        assert set(a) >= {"fid", "accuracy", "num_samples", "seed"}
        assert isinstance(a["fid"], float) and a["fid"] >= 0.0
        assert a["accuracy"] is None  # no classifier handed in

    def test_accuracy_from_classifier(self):
        probe = load_quality_probe()
        real = np.random.default_rng(0).random((32, FEAT), np.float32)
        labels = np.arange(32) % CLASSES

        def classify_fn(rows):
            return np.eye(CLASSES, dtype=np.float32)[
                np.arange(rows.shape[0]) % CLASSES]

        out = probe(lambda z: np.ones((z.shape[0], FEAT), np.float32), real,
                    z_size=Z, num_samples=16,
                    classify_fn=classify_fn, labels=labels)
        assert out["accuracy"] == 1.0

    def test_cli_sampler_chunking_preserves_the_stream(self):
        # sample_generator_rows chunked vs one-shot must see the SAME z
        # stream (the CLI's behavior-identical contract after factoring)
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_qr", os.path.join(REPO, "scripts", "quality_run.py"))
        qr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(qr)
        taken = []
        rows = qr.sample_generator_rows(
            lambda z: (taken.append(np.asarray(z)),
                       np.asarray(z) * 2.0)[1],
            Z, 10, seed=7, batch_size=4)
        one = qr.sample_generator_rows(
            lambda z: np.asarray(z) * 2.0, Z, 10, seed=7, batch_size=100)
        np.testing.assert_array_equal(rows, one)
        assert [t.shape[0] for t in taken] == [4, 4, 2]


# ===========================================================================
# canary gate
# ===========================================================================

class TestCanaryGate:
    def test_identical_engines_pass_with_the_real_probe(self, tmp_path):
        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, generation=0)
        engine = ServingEngine.from_bundle(bundle, buckets=(1, 8))
        real = np.random.default_rng(0).random((48, FEAT), np.float32)
        labels = np.arange(48) % CLASSES
        gate = CanaryGate(real, labels, num_samples=32)
        decision = gate.evaluate(engine, engine)
        assert decision.passed, decision.reason
        assert decision.candidate == decision.incumbent
        assert decision.candidate["accuracy"] is not None

    def test_fid_blowup_rejected(self):
        gate = CanaryGate(np.zeros((8, FEAT), np.float32), num_samples=8,
                          probe=lambda e: {"fid": 1.0 if e == "inc" else 99.0,
                                           "accuracy": None},
                          thresholds=CanaryThresholds(fid_ratio_max=1.5,
                                                      fid_slack=1.0))
        decision = gate.evaluate("cand", "inc")
        assert not decision.passed and "fid" in decision.reason

    def test_accuracy_drop_rejected(self):
        gate = CanaryGate(
            np.zeros((8, FEAT), np.float32), num_samples=8,
            probe=lambda e: {"fid": 1.0,
                             "accuracy": 0.9 if e == "inc" else 0.5},
            thresholds=CanaryThresholds(accuracy_drop_max=0.05))
        decision = gate.evaluate("cand", "inc")
        assert not decision.passed and "accuracy" in decision.reason
        # within the allowed drop: passes
        gate2 = CanaryGate(
            np.zeros((8, FEAT), np.float32), num_samples=8,
            probe=lambda e: {"fid": 1.0,
                             "accuracy": 0.9 if e == "inc" else 0.87},
            thresholds=CanaryThresholds(accuracy_drop_max=0.05))
        assert gate2.evaluate("cand", "inc").passed

    def test_nan_fid_fails_closed(self):
        gate = CanaryGate(np.zeros((8, FEAT), np.float32), num_samples=8,
                          probe=lambda e: {"fid": float("nan"),
                                           "accuracy": None})
        assert not gate.evaluate("cand", "inc").passed

    def test_incumbent_probe_cached_per_generation(self):
        calls = []

        class Eng:
            def __init__(self, generation):
                self.generation = generation

        # candidates fail the gate (garbage fid), so the incumbent stays
        # the incumbent — its probe must be computed exactly once
        gate = CanaryGate(
            np.zeros((8, FEAT), np.float32), num_samples=8,
            probe=lambda e: (calls.append(e.generation),
                             {"fid": 1.0 if e.generation == 0 else 900.0,
                              "accuracy": None})[1])
        inc, c1, c2 = Eng(0), Eng(1), Eng(2)
        assert not gate.evaluate(c1, inc).passed
        assert not gate.evaluate(c2, inc).passed
        # incumbent probed once, each candidate once
        assert calls == [0, 1, 2]

    def test_cache_rolls_forward_after_an_admitted_candidate(self):
        # the steady reload flow: candidate admitted -> it becomes the
        # incumbent -> the NEXT evaluate must reuse its probe (one
        # candidate probe per reload) and release the retired engine
        calls = []

        class Eng:
            def __init__(self, generation):
                self.generation = generation

        gate = CanaryGate(
            np.zeros((8, FEAT), np.float32), num_samples=8,
            probe=lambda e: (calls.append(e.generation),
                             {"fid": 1.0, "accuracy": None})[1])
        e0, e1, e2 = Eng(0), Eng(1), Eng(2)
        assert gate.evaluate(e1, e0).passed   # probes 0 and 1
        assert gate.evaluate(e2, e1).passed   # e1's probe is cached: only 2
        assert calls == [0, 1, 2]
        # the retired incumbent is no longer pinned by the cache
        assert gate._incumbent_cache[0][0] is e2


# ===========================================================================
# the fleet-admission seam (sidecar probes share the gate's decision)
# ===========================================================================

class TestFleetAdmissionSeam:
    def test_compare_probes_is_the_gate_decision(self):
        from gan_deeplearning4j_tpu.deploy import compare_probes

        t = CanaryThresholds(fid_ratio_max=1.5, fid_slack=1.0,
                             accuracy_drop_max=0.05)
        good = compare_probes({"fid": 10.0, "accuracy": 0.9},
                              {"fid": 10.0, "accuracy": 0.9}, t)
        assert good.passed and good.reason == "ok"
        fid_blown = compare_probes({"fid": 100.0, "accuracy": 0.9},
                                   {"fid": 10.0, "accuracy": 0.9}, t)
        assert not fid_blown.passed and "fid" in fid_blown.reason
        acc_drop = compare_probes({"fid": 10.0, "accuracy": 0.80},
                                  {"fid": 10.0, "accuracy": 0.90}, t)
        assert not acc_drop.passed and "accuracy" in acc_drop.reason
        # NaN fails closed, exactly like the in-process gate
        nan = compare_probes({"fid": float("nan"), "accuracy": None},
                             {"fid": 10.0, "accuracy": None}, t)
        assert not nan.passed
        # accuracy is skipped when either side has none
        no_acc = compare_probes({"fid": 10.0, "accuracy": None},
                                {"fid": 10.0, "accuracy": 0.9}, t)
        assert no_acc.passed

    def test_gate_evaluate_agrees_with_compare_probes(self):
        # the refactor seam: an injected-probe gate and a bare
        # compare_probes over the same numbers must decide identically
        from gan_deeplearning4j_tpu.deploy import compare_probes

        probes = {"cand": {"fid": 30.0, "accuracy": None},
                  "inc": {"fid": 10.0, "accuracy": None}}
        gate = CanaryGate(np.zeros((8, FEAT), np.float32), num_samples=8,
                          thresholds=CanaryThresholds(fid_ratio_max=1.5,
                                                      fid_slack=1.0),
                          probe=lambda e: probes[e])
        via_gate = gate.evaluate("cand", "inc")
        direct = compare_probes(probes["cand"], probes["inc"],
                                CanaryThresholds(fid_ratio_max=1.5,
                                                 fid_slack=1.0))
        assert via_gate.passed == direct.passed == False  # noqa: E712
        assert via_gate.reason == direct.reason

    def test_dis_feature_fid_path_round_trips(self, tmp_path):
        """--canary-feature dis_features end to end: the checkpointed
        classifier's feature vertex embeds both probe sides, and the gate
        decides on FID in that space."""
        from gan_deeplearning4j_tpu.deploy import feature_fn_from_checkpoint

        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, generation=0)
        fn = feature_fn_from_checkpoint(os.path.join(bundle, "cv.zip"),
                                        "feat_1")
        rows = np.random.default_rng(0).random((8, FEAT), dtype=np.float32)
        feats = np.asarray(fn(rows))
        assert feats.shape == (8, HIDDEN)  # the feature vertex's width
        np.testing.assert_allclose(np.asarray(fn(rows)), feats)  # pinned
        # identical engines probed through the dis-feature space pass the
        # gate with identical FIDs — the full round trip
        engine = ServingEngine.from_bundle(bundle)
        gate = CanaryGate(rows, num_samples=8, feature_fn=fn,
                          thresholds=CanaryThresholds(fid_ratio_max=1.05,
                                                      fid_slack=1e-6))
        decision = gate.evaluate(engine, engine)
        assert decision.passed
        assert decision.candidate["fid"] == pytest.approx(
            decision.incumbent["fid"])

    def test_unknown_feature_vertex_rejected(self, tmp_path):
        from gan_deeplearning4j_tpu.deploy import feature_fn_from_checkpoint

        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, generation=0)
        with pytest.raises(ValueError, match="not a vertex"):
            feature_fn_from_checkpoint(os.path.join(bundle, "cv.zip"),
                                       "nope")

    def test_cli_maps_bundle_to_dis_feature_space(self, tmp_path):
        # the manifest resolution behind the serving CLI and the sidecar
        # probe: a bundle with a classifier + feature vertex resolves,
        # one without maps to None (raw)
        from gan_deeplearning4j_tpu.deploy.canary import classifier_from_bundle

        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, generation=0)
        resolved = classifier_from_bundle(bundle)
        assert resolved == (os.path.join(bundle, "cv.zip"), "feat_1")
        bare = str(tmp_path / "bare")
        os.makedirs(bare)
        with open(os.path.join(bare, "serving.json"), "w") as fh:
            json.dump({"format_version": 1, "generator": "gen.zip"}, fh)
        assert classifier_from_bundle(bare) is None

    def test_sidecar_probe_cli_round_trips(self, tmp_path):
        """The fleet manager's sidecar: ``python -m
        gan_deeplearning4j_tpu.deploy probe`` prints one JSON probe line
        for a bundle, in the dis-feature space of a reference bundle."""
        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, generation=3)
        rng = np.random.default_rng(1)
        data = str(tmp_path / "data.npz")
        np.savez(data,
                 features=rng.random((16, FEAT), dtype=np.float32),
                 labels=np.eye(CLASSES, dtype=np.float32)[
                     rng.integers(0, CLASSES, 16)])
        out = subprocess.run(
            [sys.executable, "-m", "gan_deeplearning4j_tpu.deploy",
             "probe", "--bundle", bundle, "--data", data,
             "--samples", "8", "--feature", "dis_features"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "GDT_COMPILATION_CACHE": "off"},
        )
        assert out.returncode == 0, out.stderr[-2000:]
        probe = json.loads(out.stdout.strip().splitlines()[-1])
        assert np.isfinite(probe["fid"])
        assert probe["accuracy"] is not None
        assert probe["generation"] == 3
        assert probe["feature"] == "dis_features"


# ===========================================================================
# reload controller — end to end against real engines
# ===========================================================================

def make_service(bundle_path, **kw):
    engine = ServingEngine.from_bundle(bundle_path, buckets=(1, 8))
    return InferenceService(engine, warmup="sync", max_latency=0.001,
                            default_timeout=10.0, **kw)


def wait_for(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TestReloadController:
    def test_end_to_end_swap_to_newer_generation(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=0.05)
        service.attach_reloader(ctl)
        ctl.start()
        try:
            g1 = publish_bundle(store, gen_seed=2, step=5)
            assert wait_for(
                lambda: service.engine.generation == g1.number), (
                service.engine.generation, ctl.status())
            # the service still answers — and from the NEW weights
            r = service.sample(np.zeros((2, Z), np.float32))
            assert r.ok
            fresh = ServingEngine.from_bundle(g1.path, buckets=(1, 8),
                                              export_gauge=False)
            np.testing.assert_allclose(
                r.data, fresh.run("sample", np.zeros((2, Z), np.float32)),
                rtol=1e-6)
            assert service.batcher.metrics()["engine_swaps"] == 1
            health = service.healthz()
            assert health["generation"] == g1.number
            assert health["reload"]["swaps"] == 1
            assert wait_for(
                lambda: service.healthz()["reload"]["state"] == "idle")
        finally:
            ctl.stop()
            service.close()

    def test_canary_rejection_quarantines_and_keeps_serving(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        # probe keyed on generation: the incumbent (g0) is fine, anything
        # newer is garbage — the controller must quarantine, not serve
        gate = CanaryGate(
            np.zeros((8, FEAT), np.float32), num_samples=8,
            probe=lambda e: {"fid": 1.0 if e.generation == g0.number
                             else 500.0, "accuracy": None})
        ctl = ReloadController(service, StoreWatcher(store=store),
                               canary=gate, poll_interval=0.05)
        service.attach_reloader(ctl)
        g1 = publish_bundle(store, gen_seed=2)
        status = ctl.poll_now(wait=True)  # synchronous cycle (not started)
        assert status["rejected"] == 1 and status["state"] == "rejected"
        assert service.engine.generation == g0.number
        assert store.entry(g1.number).get("status") == "quarantined"
        assert "canary" in store.entry(g1.number).get("reason", "")
        # the rejected generation is never offered again
        assert ctl.poll_now(wait=True)["rejected"] == 1
        service.close()

    def test_candidate_missing_kinds_rejected_without_quarantine(
            self, tmp_path):
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=0.05)
        # a generator-only bundle would 404 live classify traffic
        number = store.next_number()

        def writer(d):
            gen = tiny_generator(seed=3)
            write_model(os.path.join(d, "gen.zip"), gen, gen.init(),
                        save_updater=False)
            with open(os.path.join(d, "serving.json"), "w") as fh:
                json.dump({"format_version": 1, "generator": "gen.zip",
                           "classifier": None, "feature_vertex": None,
                           "generation": number}, fh)

        g1 = store.publish(writer, step=1, extra={"kind": "serving"})
        status = ctl.poll_now(wait=True)
        assert status["rejected"] == 1
        assert service.engine.generation == g0.number
        # config mismatch, not corruption: the bytes stay published
        assert store.entry(g1.number).get("status") == "published"
        service.close()

    def test_candidate_width_mismatch_rejected(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=0.05)
        # same kinds, different z width: rows validated against the live
        # engine would error their flush after the swap — reject
        number = store.next_number()

        def writer(d):
            b = GraphBuilder(GraphConfig(seed=4))
            b.add_inputs("z").set_input_types(InputType.feed_forward(Z + 2))
            b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
            b.add_layer("g_out", OutputLayer(n_out=FEAT,
                                             activation="sigmoid",
                                             loss="xent"), "g_dense_1")
            b.set_outputs("g_out")
            gen = b.build()
            cv = tiny_classifier()
            write_model(os.path.join(d, "gen.zip"), gen, gen.init(),
                        save_updater=False)
            write_model(os.path.join(d, "cv.zip"), cv, cv.init(),
                        save_updater=False)
            with open(os.path.join(d, "serving.json"), "w") as fh:
                json.dump({"format_version": 1, "generator": "gen.zip",
                           "classifier": "cv.zip",
                           "feature_vertex": "feat_1",
                           "generation": number}, fh)

        g1 = store.publish(writer, step=1, extra={"kind": "serving"})
        status = ctl.poll_now(wait=True)
        assert status["rejected"] == 1
        assert "width" in status["last_error"]
        assert service.engine.generation == g0.number
        assert store.entry(g1.number).get("status") == "published"
        service.close()

    def test_blocking_forced_poll_returns_the_triggered_cycles_outcome(
            self, tmp_path):
        # a huge poll interval isolates the forced path: only the forced
        # poll can have performed the swap the 200 reports
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=300.0)
        service.attach_reloader(ctl)
        ctl.start()
        try:
            assert wait_for(lambda: ctl.status()["state"] == "idle")
            g1 = publish_bundle(store, gen_seed=2)
            status, body = service.handle("POST", "/admin/reload?block=1")
            assert status == 200, body
            assert body["reload"]["swaps"] == 1
            assert service.engine.generation == g1.number
        finally:
            ctl.stop()
            service.close()

    def test_directory_mode_primed_with_the_served_bundle(self, tmp_path):
        # the bundle the server booted from must not be re-offered as a
        # "new" candidate on the first poll (spurious warm + swap)
        bundle = str(tmp_path / "bundle")
        write_bundle(bundle, gen_seed=1, generation=0)
        service = make_service(bundle)
        ctl = ReloadController(service, StoreWatcher(path=bundle),
                               poll_interval=0.05)
        assert ctl.poll_now(wait=True)["swaps"] == 0
        assert ctl.status()["state"] == "idle"
        # a genuinely newer manifest still reloads
        write_bundle(bundle, gen_seed=2, generation=1)
        assert ctl.poll_now(wait=True)["swaps"] == 1
        assert service.engine.generation == 1
        service.close()

    def test_admin_reload_routes(self, tmp_path):
        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        # no reload plane attached -> 409 (nothing to poll)
        status, body = service.handle("POST", "/admin/reload")
        assert status == 409 and "no reload plane" in body["error"]
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=0.05)
        service.attach_reloader(ctl)
        g1 = publish_bundle(store, gen_seed=2)
        # block=1 waits for the cycle: by the 200 the swap has happened
        status, body = service.handle("POST", "/admin/reload?block=1")
        assert status == 200, body
        assert body["reload"]["swaps"] == 1
        assert service.engine.generation == g1.number
        # async form answers 202 with the reload state
        status, body = service.handle("POST", "/admin/reload")
        assert status == 202 and "reload" in body
        # busy -> 409, mirroring /debug/trace
        with ctl._lock:
            ctl._busy = True
        status, body = service.handle("POST", "/admin/reload?block=1")
        assert status == 409 and "in progress" in body["error"]
        with ctl._lock:
            ctl._busy = False
        service.close()

    def test_candidate_state_and_gauge_follow_the_swap(self, tmp_path):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        store = CheckpointStore(str(tmp_path / "store"), keep_last=10)
        g0 = publish_bundle(store, gen_seed=1)
        service = make_service(g0.path)
        gauge = get_registry().gauge("serving_generation").labels()
        assert gauge.value == g0.number
        # a candidate engine built with export_gauge=False never claims
        # the gauge while warming/canarying
        g1 = publish_bundle(store, gen_seed=2)
        candidate = ServingEngine.from_bundle(g1.path, buckets=(1, 8),
                                              export_gauge=False)
        assert gauge.value == g0.number
        ctl = ReloadController(service, StoreWatcher(store=store),
                               poll_interval=0.05,
                               build=lambda cand, live: candidate)
        ctl.poll_now(wait=True)
        assert service.engine is candidate
        assert gauge.value == g1.number
        state = get_registry().gauge("deploy_candidate_state").labels()
        assert state.value == 0  # back to idle
        service.close()


# ===========================================================================
# supervisor serve-publish cadence
# ===========================================================================

class FakeServeExperiment:
    """Step counter + serving-bundle publisher; no jax (the pattern of
    tests/test_resilience.FakeExperiment, plus publish_for_serving)."""

    def __init__(self, config):
        self.config = config
        self.batch_counter = 0
        self.dis_state = self.gan_state = self.cv_state = None
        self.gen_params = None

    def train_iteration(self, feats, labels):
        pass

    def save_models(self, directory=None):
        with open(os.path.join(directory, "state.txt"), "w") as fh:
            fh.write(str(self.batch_counter))

    def load_models(self, directory=None):
        with open(os.path.join(directory, "state.txt")) as fh:
            self.batch_counter = int(fh.read())
        return self.batch_counter

    def publish_for_serving(self, directory=None, store=None):
        number = store.next_number()
        step = self.batch_counter

        def writer(d):
            with open(os.path.join(d, "serving.json"), "w") as fh:
                json.dump({"format_version": 1, "generation": number,
                           "step": step}, fh)

        gen = store.publish(writer, step=step, extra={"kind": "serving"})
        return {"generation": gen.number, "directory": gen.path}


def serve_supervisor(tmp_path, sup_cfg):
    import dataclasses

    @dataclasses.dataclass
    class Cfg:
        batch_size_train: int = 4

    sup = TrainingSupervisor(
        Cfg(), sup_cfg,
        np.zeros((16, 3), np.float32), np.zeros((16, 2), np.float32),
        store_root=os.path.join(str(tmp_path), "store"),
        serve_store_root=os.path.join(str(tmp_path), "serve_store"),
        sleep=lambda s: None,
        experiment_factory=FakeServeExperiment,
    )
    sup.state_digests = lambda exp: {"fake": str(exp.batch_counter)}
    return sup


class TestSupervisorServePublish:
    def test_serve_cadence_and_final_publish(self, tmp_path):
        sup = serve_supervisor(tmp_path, SupervisorConfig(
            total_steps=10, publish_every=4, serve_publish_every=3))
        out = sup.run()
        assert out["status"] == "completed"
        # cadence 3, 6, 9 plus the final off-cadence state at 10
        assert [e["step"] for e in sup.events
                if e["event"] == "serve_publish"] == [3, 6, 9, 10]
        assert out["serve_publish_count"] == 4
        newest = sup.serve_store.latest_valid()
        assert newest.number == out["final_serve_generation"]
        assert newest.step == 10
        assert newest.manifest.get("kind") == "serving"
        # the bundle is watcher-visible
        assert StoreWatcher(
            store=sup.serve_store).poll_once().generation == newest.number
        # training checkpoints stayed in their own store: 4, 8, 10
        assert [e["step"] for e in sup.events
                if e["event"] == "publish"] == [4, 8, 10]

    def test_serve_cadence_defaults_to_publish_every(self, tmp_path):
        sup = serve_supervisor(tmp_path, SupervisorConfig(
            total_steps=10, publish_every=4))
        sup.run()
        assert [e["step"] for e in sup.events
                if e["event"] == "serve_publish"] == [4, 8, 10]

    def test_no_serve_store_means_no_serve_publishes(self, tmp_path):
        import dataclasses

        @dataclasses.dataclass
        class Cfg:
            batch_size_train: int = 4

        sup = TrainingSupervisor(
            Cfg(), SupervisorConfig(total_steps=4, publish_every=2),
            np.zeros((16, 3), np.float32), np.zeros((16, 2), np.float32),
            store_root=os.path.join(str(tmp_path), "store"),
            sleep=lambda s: None, experiment_factory=FakeServeExperiment,
        )
        sup.state_digests = lambda exp: {"fake": str(exp.batch_counter)}
        out = sup.run()
        assert out["serve_publish_count"] == 0
        assert not [e for e in sup.events if e["event"] == "serve_publish"]


# ===========================================================================
# the subprocess drill (slow)
# ===========================================================================

@pytest.mark.slow
class TestReloadDrill:
    def test_drill_smoke(self, tmp_path):
        out = tmp_path / "reload_drill.json"
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        # the suite's 8 fake host devices would multiply every warmup by 8
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "scripts/reload_drill.py", "--smoke",
             "--output", str(out)],
            cwd=REPO, capture_output=True, text=True, timeout=900, env=env,
        )
        assert proc.returncode == 0, (
            proc.stdout[-4000:] + "\n" + proc.stderr[-2000:])
        payload = json.loads(out.read_text())
        assert payload["ok"]
        assert payload["invariants"]["swaps_ge_2"]
        assert payload["invariants"]["poison_quarantined"]
        assert payload["invariants"]["zero_lost"]
        assert payload["results"]["swap_phase"]["swaps_observed"] >= 2
