"""Per-step RNG threading (round-2 VERDICT weak #5).

Dropout-style layers must see a FRESH key every optimizer step in both
training paths — the phased ``GraphTrainer.train_step`` and the fused
alternating iteration — or every iteration reuses identical masks (the
reference topologies carry no dropout, dl4jGANComputerVision.java:117-314,
so the bug would only bite future families; these tests pin the contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
from gan_deeplearning4j_tpu.models import registry
from gan_deeplearning4j_tpu.models.registry import GanFamily
from gan_deeplearning4j_tpu.nn import (
    ComputationGraph,
    DenseLayer,
    DropoutLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.optim import RmsProp
from gan_deeplearning4j_tpu.parallel import GraphTrainer, TrainState

FEATURES = 8
Z = 2


def _cfg(lr: float = 0.0) -> GraphConfig:
    return GraphConfig(
        seed=666, default_activation="tanh", weight_init="xavier",
        l2=0.0, gradient_clip="elementwise", gradient_clip_value=1.0,
        updater=RmsProp(lr, 1e-8, 1e-8), optimization_algo="sgd",
    )


def _dropout_dis_layers(b: GraphBuilder, prefix: str, lr: float, inp: str) -> str:
    up = RmsProp(lr, 1e-8, 1e-8)
    b.add_layer(f"{prefix}_dense_1", DenseLayer(n_out=16, updater=up), inp)
    b.add_layer(f"{prefix}_drop_2", DropoutLayer(rate=0.5), f"{prefix}_dense_1")
    b.add_layer(
        f"{prefix}_output_3",
        OutputLayer(n_out=1, activation="sigmoid", loss="xent", updater=up),
        f"{prefix}_drop_2",
    )
    return f"{prefix}_output_3"


def _build_dis(cfg) -> ComputationGraph:
    b = GraphBuilder(_cfg())
    b.add_inputs("dis_input_0")
    b.set_input_types(InputType.feed_forward(FEATURES))
    b.set_outputs(_dropout_dis_layers(b, "dis", 0.0, "dis_input_0"))
    return b.build()


def _build_gen(cfg) -> ComputationGraph:
    b = GraphBuilder(_cfg())
    b.add_inputs("gen_input_0")
    b.set_input_types(InputType.feed_forward(Z))
    b.add_layer(
        "gen_dense_1",
        DenseLayer(n_out=FEATURES, activation="sigmoid", updater=RmsProp(0.0, 1e-8, 1e-8)),
        "gen_input_0",
    )
    b.set_outputs("gen_dense_1")
    return b.build()


def _build_gan(cfg) -> ComputationGraph:
    b = GraphBuilder(_cfg())
    b.add_inputs("gan_input_0")
    b.set_input_types(InputType.feed_forward(Z))
    b.add_layer(
        "gan_dense_1",
        DenseLayer(n_out=FEATURES, activation="sigmoid", updater=RmsProp(0.0, 1e-8, 1e-8)),
        "gan_input_0",
    )
    b.set_outputs(_dropout_dis_layers(b, "gan_dis", 0.0, "gan_dense_1"))
    return b.build()


_DIS_TO_GAN = {
    "dis_dense_1": "gan_dis_dense_1",
    "dis_output_3": "gan_dis_output_3",
}
_GAN_TO_GEN = {"gan_dense_1": "gen_dense_1"}


@pytest.fixture
def dropout_family():
    fam = GanFamily(
        name="_dropout_test",
        make_model_config=lambda cfg: cfg,
        build_discriminator=_build_dis,
        build_generator=_build_gen,
        build_gan=_build_gan,
        sync_maps=lambda cfg: (_DIS_TO_GAN, _GAN_TO_GEN),
    )
    registry.register(fam, overwrite=True)
    yield fam
    registry.unregister("_dropout_test")


def test_train_step_key_varies_with_step():
    """Same params + same batch at different step counters must produce
    different dropout masks (the step is folded into the key inside the
    jitted program); the same step must reproduce bit-identically."""
    graph = _build_dis(None)
    trainer = GraphTrainer(graph, donate=False)
    state0 = trainer.init_state()
    x = np.linspace(0, 1, 4 * FEATURES, dtype=np.float32).reshape(4, FEATURES)
    y = np.ones((4, 1), np.float32)

    _, loss_step0 = trainer.train_step(state0, x, y)
    _, loss_step0_again = trainer.train_step(state0, x, y)
    state1 = TrainState(state0.params, state0.opt_state, state0.step + 1)
    _, loss_step1 = trainer.train_step(state1, x, y)

    assert float(loss_step0) == float(loss_step0_again)  # deterministic
    assert float(loss_step0) != float(loss_step1)  # fresh mask per step


def test_fused_iteration_masks_vary_per_iteration(dropout_family):
    """Fused-path regression: with ALL learning rates 0 (params frozen), a
    zeroed generator (constant fake batch), and a fixed real batch, the only
    thing that can change between iterations is the per-step rng — so the
    d-loss sequence must NOT be constant. Under the old constant
    ``PRNGKey(0)`` loss key it was."""
    cfg = ExperimentConfig(
        model_family="_dropout_test", batch_size_train=4, batch_size_pred=4,
        num_features=FEATURES, height=FEATURES, width=1, channels=1,
        z_size=Z, num_iterations=3, save_models=False,
        dis_learning_rate=0.0, gen_learning_rate=0.0, l2=0.0,
    )
    exp = GanExperiment(cfg)
    assert exp._fused is not None, "test must exercise the fused path"
    # zero the sampler so the fake batch is z-independent (sigmoid(0)=0.5)
    exp.gen_params = jax.tree_util.tree_map(jnp.zeros_like, exp.gen_params)

    feats = np.linspace(0, 1, 4 * FEATURES, dtype=np.float32).reshape(4, FEATURES)
    labels = np.eye(cfg.num_classes, dtype=np.float32)[np.arange(4) % cfg.num_classes]
    losses = [float(exp.train_iteration(feats, labels)["d_loss"]) for _ in range(3)]
    assert len(set(losses)) > 1, f"dropout masks repeated across iterations: {losses}"
