"""Cross-replica weight-update sharding (parallel/update_sharding.py).

The contract under test (docs/RESILIENCE.md, update-sharding section):

- the partition is the checkpoint partition — compute shard k's wholly
  resident updater keys ARE checkpoint shard k's (``serializer.
  shard_keys`` on the same flat namespace), element-split leaves aside;
- the single-model trainer step keeps grads and updater state
  DIGEST-EXACT against the replicated trainer at mesh 1/2/4 (packing is
  reshape/slice/concat and every in-tree updater is elementwise, with
  ``exact_grads`` pinning the backward replicated), while params track
  within a few ulps per step (XLA instruction-selection variance on the
  delta's divide/rsqrt between the two program shapes);
- the fused experiment program is tolerance-exact across modes (ulp
  reassociation, amplified chaotically — so cross-mode parity pins ONE
  iteration) while sharded-mode training itself stays deterministic;
- per-device resident updater bytes ≈ 1/N of replicated;
- checkpoints stay tree-format and round-trip bit-exactly across mesh
  sizes AND across modes (sharded-written -> replicated restore and
  back);
- the new placement code stays green under JG013/JG018.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
from gan_deeplearning4j_tpu.optim import GraphOptimizer
from gan_deeplearning4j_tpu.parallel import (
    GraphTrainer,
    PackedOptState,
    TrainState,
    UpdateShardingPlan,
)
from gan_deeplearning4j_tpu.parallel.update_sharding import flat_model_keys
from gan_deeplearning4j_tpu.resilience.supervisor import TrainingSupervisor
from gan_deeplearning4j_tpu.runtime import TpuEnvironment
from gan_deeplearning4j_tpu.utils.serializer import (
    shard_assignment,
    shard_keys,
)

from tests.test_parallel import small_classifier, toy_data


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compile_cache():
    """Same guard as tests/test_resilience.py: this module serially
    builds and tears down many near-identical fused programs — the
    write-then-load-in-process pattern that turns the XLA:CPU persistent
    cache's unsafe AOT loader into glibc heap corruption ('corrupted
    double-linked list' → segfault; reproduced in this module inside the
    full tier-1 run). Persistent cache off for the module; jax memoizes
    the cache-used decision, so reset it on both edges."""
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()  # drop the memoized "cache is used" decision
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    _cc.reset_cache()


def leaf_bytes(tree):
    return [np.asarray(l).tobytes() for l in jax.tree_util.tree_leaves(tree)]


def mesh_of(n):
    return TpuEnvironment(device_limit=n).make_mesh()


# ---------------------------------------------------------------------------
# the partition function
# ---------------------------------------------------------------------------

class TestShardAssignment:
    SIZES = {
        "m/params/a/W": 1000, "m/params/a/b": 10,
        "m/params/c/W": 800, "m/params/c/b": 8,
        "m/updater/a/W/cache": 1000, "m/updater/a/b/cache": 10,
        "m/updater/c/W/cache": 800, "m/updater/c/b/cache": 8,
        "m/step": 1,
    }

    def test_partition_is_exact_and_deterministic(self):
        for count in (1, 2, 3):
            assign = shard_assignment(self.SIZES, count)
            assert set(assign) == set(self.SIZES)
            assert set(assign.values()) <= set(range(count))
            # dict ordering must not matter
            shuffled = dict(sorted(self.SIZES.items(), reverse=True))
            assert shard_assignment(shuffled, count) == assign

    def test_partition_balances_each_kind_bucket(self):
        # round-robin's failure mode: W/b alternation parks every big W
        # on one shard — the greedy must spread the updater bytes
        assign = shard_assignment(self.SIZES, 2)
        loads = [0, 0]
        for k, s in self.SIZES.items():
            if "/updater/" in k:
                loads[assign[k]] += s
        assert max(loads) <= 1000 + 18  # biggest leaf bounds the skew

    def test_shard_keys_mapping_mode_matches_assignment(self):
        per_shard = [set(shard_keys(self.SIZES, k, 2)) for k in range(2)]
        assert per_shard[0] | per_shard[1] == set(self.SIZES)
        assert not (per_shard[0] & per_shard[1])
        assign = shard_assignment(self.SIZES, 2)
        for k in range(2):
            assert per_shard[k] == {key for key, s in assign.items()
                                    if s == k}

    def test_shard_keys_list_mode_stays_round_robin(self):
        # PR 9's rule for bare key lists is unchanged — old callers and
        # old generations keep their behavior
        keys = [f"k{i}" for i in range(7)]
        assert shard_keys(keys, 1, 3) == sorted(keys)[1::3]


# ---------------------------------------------------------------------------
# trainer-level: digest-exact parity + layout invariants
# ---------------------------------------------------------------------------

class TestTrainerParity:
    @pytest.mark.parametrize("n", [1, 2, 4])
    def test_sharded_step_parity(self, n):
        graph = small_classifier()
        x, y = toy_data(64)
        mesh = mesh_of(n)
        base = GraphTrainer(graph, mesh=mesh, donate=False)
        sh = GraphTrainer(graph, mesh=mesh, donate=False,
                          shard_updates=True, model_name="m")
        bs, ss = base.init_state(), sh.init_state()
        # fresh inits must already agree byte-for-byte
        assert leaf_bytes(bs.opt_state) == leaf_bytes(
            sh.plan.unpack_state(ss.opt_state))
        bs, _ = base.train_step(bs, jnp.asarray(x), jnp.asarray(y))
        ss, _ = sh.train_step(ss, jnp.asarray(x), jnp.asarray(y))
        # after ONE step, grads + updater state are BIT-exact
        # (exact_grads pins the backward replicated; the state update is
        # elementwise on the same bytes) — params may differ by a few
        # ulps: XLA selects divide/rsqrt and fma forms per program shape
        # for the delta, the documented-tolerance half of the contract
        assert leaf_bytes(bs.opt_state) == leaf_bytes(
            sh.plan.unpack_state(ss.opt_state))

        def params_close(a, b):
            for lb, ls in zip(jax.tree_util.tree_leaves(a.params),
                              jax.tree_util.tree_leaves(b.params)):
                np.testing.assert_allclose(
                    np.asarray(ls, np.float64), np.asarray(lb, np.float64),
                    rtol=1e-5, atol=1e-5)

        params_close(bs, ss)
        # further steps feed the ulp-sized param difference back through
        # the grads, so EVERYTHING is tolerance from here — still tight
        # on a converging (non-adversarial) workload
        for _ in range(2):
            bs, _ = base.train_step(bs, jnp.asarray(x), jnp.asarray(y))
            ss, _ = sh.train_step(ss, jnp.asarray(x), jnp.asarray(y))
        params_close(bs, ss)
        tree = sh.plan.unpack_state(ss.opt_state)
        for lb, ls in zip(jax.tree_util.tree_leaves(bs.opt_state),
                          jax.tree_util.tree_leaves(tree)):
            np.testing.assert_allclose(
                np.asarray(ls, np.float64), np.asarray(lb, np.float64),
                rtol=1e-5, atol=1e-5)

    def test_packed_rows_placed_on_data_axis(self):
        graph = small_classifier()
        mesh = mesh_of(2)
        sh = GraphTrainer(graph, mesh=mesh, shard_updates=True)
        ss = sh.init_state()
        assert isinstance(ss.opt_state, PackedOptState)
        for leaf in jax.tree_util.tree_leaves(ss.opt_state):
            spec = leaf.sharding.spec
            assert tuple(spec) == ("data",)
            assert leaf.shape[0] == 2

    def test_plan_partition_matches_checkpoint_shards(self):
        # THE 1:1 mapping: compute shard k's wholly-resident updater keys
        # == the updater keys of checkpoint shard k over the same
        # namespace (element-split keys span every shard and are
        # accounted separately)
        graph = small_classifier()
        mesh = mesh_of(2)
        sh = GraphTrainer(graph, mesh=mesh, shard_updates=True,
                          model_name="m")
        ss = sh.init_state()
        sizes = flat_model_keys("m", ss.params, sh.optimizer.base)
        split = set(sh.plan.element_split_state_keys())
        for k in range(2):
            mine = set(sh.plan.updater_keys_for_shard(k))
            checkpoint = {key for key in shard_keys(sizes, k, 2)
                          if "/updater/" in key} - split
            assert mine == checkpoint

    def test_pack_unpack_round_trip_bit_exact(self):
        graph = small_classifier()
        mesh = mesh_of(4)
        sh = GraphTrainer(graph, mesh=mesh, shard_updates=True)
        ss = sh.init_state()
        tree = sh.plan.unpack_state(ss.opt_state)
        repacked = sh.plan.pack_state(tree)
        assert leaf_bytes(ss.opt_state) == leaf_bytes(repacked)

    def test_init_packed_equals_tree_init_packed(self):
        # the optim layer's shard-slice init (init_state_packed) must
        # produce the same bytes as packing the replicated tree init
        graph = small_classifier()
        mesh = mesh_of(2)
        sh = GraphTrainer(graph, mesh=mesh, shard_updates=True)
        ss = sh.init_state()
        base = GraphOptimizer(graph)
        tree = base.init(jax.device_get(ss.params))
        assert leaf_bytes(ss.opt_state) == leaf_bytes(
            sh.plan.pack_state(tree))

    def test_shard_updates_requires_mesh(self):
        with pytest.raises(ValueError, match="requires a mesh"):
            GraphTrainer(small_classifier(), shard_updates=True)


# ---------------------------------------------------------------------------
# optim layer: shard-slice init
# ---------------------------------------------------------------------------

class TestOptimShardSlice:
    def test_init_state_packed_broadcasts_scalars(self):
        from gan_deeplearning4j_tpu.optim import Adam, RmsProp

        flat = jnp.ones((7,), jnp.float32)
        rms = RmsProp(0.01).init_state_packed(flat)
        assert rms["cache"].shape == (7,)
        adam = Adam(0.01).init_state_packed(flat)
        assert adam["m"].shape == (7,) and adam["v"].shape == (7,)
        assert adam["t"].shape == (7,)  # scalar t broadcast per element
        assert adam["t"].dtype == jnp.int32

    def test_graph_optimizer_init_accepts_key_slice(self):
        graph = small_classifier()
        opt = GraphOptimizer(graph)
        params = graph.init(0)
        full = opt.init(params)
        keys = [(layer, pname) for layer, d in full.items() for pname in d]
        half = opt.init(params, keys=keys[: len(keys) // 2])
        got = [(layer, pname) for layer, d in half.items() for pname in d]
        assert sorted(got) == sorted(keys[: len(keys) // 2])

    def test_state_structs_matches_init(self):
        graph = small_classifier()
        opt = GraphOptimizer(graph)
        params = graph.init(0)
        structs = opt.state_structs(params)
        real = opt.init(params)
        assert jax.tree_util.tree_structure(structs) == \
            jax.tree_util.tree_structure(real)
        for s, r in zip(jax.tree_util.tree_leaves(structs),
                        jax.tree_util.tree_leaves(real)):
            assert tuple(s.shape) == tuple(jnp.shape(r))
            assert s.dtype == jnp.asarray(r).dtype


# ---------------------------------------------------------------------------
# experiment-level: fused parity (tolerance), residency, restores
# ---------------------------------------------------------------------------

def tiny_config(tmp_path, **overrides) -> ExperimentConfig:
    base = dict(
        batch_size_train=16, batch_size_pred=32, num_iterations=2,
        latent_grid=4, data_dir=str(tmp_path / "data"),
        output_dir=str(tmp_path / f"out{len(os.listdir(tmp_path)) if tmp_path.exists() else 0}"),
        save_models=False, distributed="pmean",
    )
    base.update(overrides)
    return ExperimentConfig(**base)


def real_batch(b=16):
    rng = np.random.default_rng(0)
    x = rng.random((b, 784), dtype=np.float32)
    y = np.zeros((b, 10), np.float32)
    y[np.arange(b), rng.integers(0, 10, b)] = 1.0
    return x, y


class TestExperimentUpdateSharding:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="pmean"):
            ExperimentConfig(update_sharding=True).validate()
        with pytest.raises(ValueError, match="pmean"):
            ExperimentConfig(update_sharding=True,
                             distributed="param_averaging").validate()

    @pytest.mark.slow
    def test_fused_parity_residency_and_mapping(self, tmp_path):
        """One build of the replicated/sharded pair covers: cross-mode
        parity (documented tolerance, one fused iteration), per-device
        resident updater bytes ≈ 1/N, the compute↔checkpoint key
        mapping on the REAL model, and the sharded->replicated
        whole-file checkpoint round trip."""
        x, y = real_batch()
        mesh = mesh_of(2)
        base = GanExperiment(tiny_config(tmp_path), mesh=mesh)
        shard = GanExperiment(
            tiny_config(tmp_path, update_sharding=True), mesh=mesh)
        base.train_iteration(x, y)
        shard.train_iteration(x, y)

        # parity: one fused iteration within the documented tolerance
        db, ds = base.digest_states(), shard.digest_states()
        assert set(db) == set(ds)
        for name in db:
            for lb, ls in zip(jax.tree_util.tree_leaves(db[name]),
                              jax.tree_util.tree_leaves(ds[name])):
                lb64 = np.asarray(lb, np.float64)
                ls64 = np.asarray(ls, np.float64)
                np.testing.assert_allclose(
                    ls64, lb64, rtol=5e-2, atol=1e-3,
                    err_msg=f"{name} diverged past the documented "
                            f"tolerance after ONE fused iteration")

        # residency: updater bytes per device ~ 1/N of replicated
        def updater_bytes(exp):
            per_dev = {}
            for st in (exp.dis_state, exp.gan_state, exp.cv_state):
                for leaf in jax.tree_util.tree_leaves(st.opt_state):
                    for s in leaf.addressable_shards:
                        per_dev[s.device.id] = (
                            per_dev.get(s.device.id, 0) + s.data.nbytes)
            return per_dev

        rep = max(updater_bytes(base).values())
        sh = max(updater_bytes(shard).values())
        assert sh <= rep * 1.35 / 2, (sh, rep)

        # compute↔checkpoint mapping on the real model's namespace
        flat = shard._flat_state()
        split = set()
        trainers = (shard.dis_trainer, shard.gan_trainer, shard.cv_trainer)
        for tr in trainers:
            split |= set(tr.plan.element_split_state_keys())
        for k in range(2):
            mine = set()
            for tr in trainers:
                mine |= set(tr.plan.updater_keys_for_shard(k))
            checkpoint = {key for key in shard_keys(flat, k, 2)
                          if "/updater/" in key} - split
            assert mine == checkpoint

        # whole-file checkpoints from a sharded run restore bit-exactly
        # on a replicated experiment (tree format unchanged)
        out = tmp_path / "full"
        out.mkdir()
        shard.save_models(directory=str(out))
        plain = GanExperiment(tiny_config(tmp_path), mesh=mesh_of(1))
        plain.load_models(directory=str(out))
        assert TrainingSupervisor.state_digests(plain) == \
            TrainingSupervisor.state_digests(shard)

    @pytest.mark.slow
    def test_elastic_sharded_generation_across_mesh_sizes(self, tmp_path):
        """A sharded-updater generation written at mesh M=2 restores
        bit-exactly at mesh N=4 (sharded) and N=1 (replicated) — the
        acceptance criterion's both-directions reshard."""
        x, y = real_batch()
        writer = GanExperiment(
            tiny_config(tmp_path, update_sharding=True), mesh=mesh_of(2))
        for _ in range(2):
            writer.train_iteration(x, y)
        gen = tmp_path / "gen"
        gen.mkdir()
        for k in range(2):
            writer.save_model_shard(str(gen), k, 2)

        reader4 = GanExperiment(
            tiny_config(tmp_path, update_sharding=True), mesh=mesh_of(4))
        reader4.load_models(directory=str(gen))
        assert TrainingSupervisor.state_digests(reader4) == \
            TrainingSupervisor.state_digests(writer)
        # the restored packed rows are live on the 4-shard partition
        # (the determinism test proves sharded states train; compiling
        # the mesh-4 fused program here would cost ~1 min of tier-1)
        for leaf in jax.tree_util.tree_leaves(reader4.dis_state.opt_state):
            assert leaf.shape[0] == 4
            assert tuple(leaf.sharding.spec) == ("data",)

        reader1 = GanExperiment(tiny_config(tmp_path), mesh=mesh_of(1))
        reader1.load_models(directory=str(gen))
        assert TrainingSupervisor.state_digests(reader1) == \
            TrainingSupervisor.state_digests(writer)

    @pytest.mark.slow
    def test_sharded_mode_is_deterministic_and_scan_path_works(
            self, tmp_path):
        """Two sharded runs are bit-identical (within-mode determinism —
        what the supervisor's resume contract rests on), including
        through the lax.scan device loop."""
        x, y = real_batch()
        a = GanExperiment(
            tiny_config(tmp_path, update_sharding=True), mesh=mesh_of(2))
        b = GanExperiment(
            tiny_config(tmp_path, update_sharding=True), mesh=mesh_of(2))
        for _ in range(2):
            a.train_iteration(x, y)
            b.train_iteration(x, y)
        wins = np.stack([x, x])
        labs = np.stack([y, y])
        a.train_iterations(wins, labs)
        b.train_iterations(wins, labs)
        assert TrainingSupervisor.state_digests(a) == \
            TrainingSupervisor.state_digests(b)


# ---------------------------------------------------------------------------
# mesh-mode surfacing: which updater shard did this worker write
# ---------------------------------------------------------------------------

class TestShardSurfacing:
    def test_supervisor_mesh_publish_surfaces_shard_index(self, tmp_path):
        # the fake experiment exercises the supervisor's mesh publish
        # plumbing without a jax compile — what's under test is that the
        # summary/events now NAME the shard each worker wrote
        from gan_deeplearning4j_tpu.resilience import SupervisorConfig
        from gan_deeplearning4j_tpu.resilience.mesh import MeshCoordinator
        from gan_deeplearning4j_tpu.resilience.supervisor import (
            TrainingSupervisor as Sup,
        )
        from tests.test_resilience import FakeExperiment

        store_root = str(tmp_path / "store")
        os.makedirs(store_root)
        cfg = tiny_config(tmp_path, distributed="none",
                          num_iterations=2, save_models=False)
        mesh = MeshCoordinator(store_root, worker=0, world_size=1,
                               token="t0", timeout_s=30.0)
        x = np.zeros((16, 784), np.float32)
        y = np.zeros((16, 10), np.float32)
        sup = Sup(cfg, SupervisorConfig(total_steps=2, publish_every=1),
                  features=x, labels=y, store_root=store_root, mesh=mesh,
                  experiment_factory=FakeExperiment)
        # the fake has no states to digest — bypass the digest hook
        sup.state_digests = lambda exp: {"fake": str(exp.batch_counter)}
        summary = sup.run()
        assert summary["status"] == "completed"
        shard = summary["updater_shard"]
        assert shard["shard_index"] == 0 and shard["shard_count"] == 1
        assert shard["files"], "shard file names must be surfaced"
        publishes = [e for e in summary["events"]
                     if e["event"] == "publish"]
        assert publishes and all(
            e["shard_index"] == 0 and e["shard_files"]
            for e in publishes)


# ---------------------------------------------------------------------------
# jaxlint: the new placement code stays green
# ---------------------------------------------------------------------------

class TestLintGreen:
    def test_jg013_jg018_green_on_update_sharding_code(self):
        from gan_deeplearning4j_tpu.analysis.engine import analyze_paths

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = [
            os.path.join(root, "gan_deeplearning4j_tpu", "parallel",
                         "update_sharding.py"),
            os.path.join(root, "gan_deeplearning4j_tpu", "parallel",
                         "trainer.py"),
        ]
        report = analyze_paths(paths)
        assert [f.code for f in report.active] == []
