"""End-to-end harness tests: the alternating GAN loop on small synthetic
MNIST — the SURVEY §4 acceptance slice (shapes, weight-sync coherence,
exports, checkpoints), on the CPU fake mesh."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import ArrayDataSetIterator
from gan_deeplearning4j_tpu.data.dataset import one_hot_np
from gan_deeplearning4j_tpu.data.mnist import synthetic_mnist
from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
from gan_deeplearning4j_tpu.harness.experiment import latent_grid
from gan_deeplearning4j_tpu.utils import read_model


def tiny_config(tmp_path, **overrides) -> ExperimentConfig:
    base = dict(
        batch_size_train=16,
        batch_size_pred=32,
        num_iterations=2,
        latent_grid=4,
        data_dir=str(tmp_path / "data"),
        output_dir=str(tmp_path / "out"),
        save_models=True,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


def iterators(batch_train=16, batch_pred=32, n_train=64, n_test=32):
    (xtr, ytr), (xte, yte) = synthetic_mnist(n_train, n_test)
    train = ArrayDataSetIterator(xtr, one_hot_np(ytr, 10), batch_size=batch_train)
    test = ArrayDataSetIterator(xte, one_hot_np(yte, 10), batch_size=batch_pred)
    return train, test


class TestLatentGrid:
    def test_grid_layout(self):
        g = latent_grid(10, 2)
        assert g.shape == (100, 2)
        assert g.min() == -1.0 and g.max() == 1.0
        g3 = latent_grid(4, 3)
        assert g3.shape == (16, 3)
        np.testing.assert_array_equal(g3[:, 2], 0.0)


class TestConfig:
    def test_defaults_match_reference(self):
        c = ExperimentConfig()
        assert (c.batch_size_train, c.batch_size_pred) == (200, 500)
        assert (c.num_features, c.num_classes, c.num_classes_dis) == (784, 10, 1)
        assert c.num_iterations == 2 and c.z_size == 2 and c.seed == 666
        assert (c.dis_learning_rate, c.gen_learning_rate, c.frozen_learning_rate) == (
            0.002, 0.004, 0.0,
        )
        assert c.averaging_frequency == 10 and c.batch_size_per_worker == 200

    def test_cli_and_json_overrides(self, tmp_path):
        c = ExperimentConfig.from_args(["--num-iterations", "5", "--seed", "1"])
        assert c.num_iterations == 5 and c.seed == 1
        p = tmp_path / "c.json"
        ExperimentConfig(num_iterations=7).to_json(str(p))
        c2 = ExperimentConfig.from_args(["--config", str(p), "--seed", "3"])
        assert c2.num_iterations == 7 and c2.seed == 3

    def test_validation(self):
        with pytest.raises(ValueError):
            ExperimentConfig(num_features=100).validate()
        with pytest.raises(ValueError):
            ExperimentConfig(distributed="spark").validate()


class TestExperimentLoop:
    @pytest.mark.slow
    def test_two_iterations_end_to_end(self, tmp_path):
        cfg = tiny_config(tmp_path)
        exp = GanExperiment(cfg)
        train, test = iterators()
        result = exp.run(train, test)
        assert result["iterations"] == 2
        for h in result["history"]:
            assert np.isfinite([h["d_loss"], h["g_loss"], h["cv_loss"]]).all()
        # exports exist with the right shapes
        manifold = np.loadtxt(
            os.path.join(cfg.output_dir, "mnist_out_1.csv"), delimiter=","
        )
        assert manifold.shape == (16, 784)
        assert manifold.min() >= 0.0 and manifold.max() <= 1.0  # sigmoid output
        preds = np.loadtxt(
            os.path.join(cfg.output_dir, "mnist_test_predictions_1.csv"), delimiter=","
        )
        assert preds.shape == (32, 10)
        np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)  # softmax rows
        # all four checkpoints restorable
        for name in ("dis", "gan", "gen", "CV"):
            path = os.path.join(cfg.output_dir, f"mnist_{name}_model.zip")
            graph, params, _, _ = read_model(path)
            assert params

    @pytest.mark.slow
    def test_weight_sync_coherence(self, tmp_path):
        """After an iteration: gan frozen tail == dis, gen == gan generator
        layers, cv features == dis features — the invariant the reference's
        38 setParam calls maintain (:429-542)."""
        from gan_deeplearning4j_tpu.models.dcgan_mnist import (
            DIS_TO_CV, DIS_TO_GAN, GAN_TO_GEN,
        )

        cfg = tiny_config(tmp_path, num_iterations=1, save_models=False)
        exp = GanExperiment(cfg)
        train, _ = iterators()
        exp.run(train)
        for src, dst in GAN_TO_GEN.items():
            for pname, v in exp.gan_state.params[src].items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(exp.gen_params[dst][pname])
                )
        # cv features were synced BEFORE the cv fit: weights stay equal
        # (frozen, LR 0) but cv-side BN stats advance during its own step
        for src, dst in DIS_TO_CV.items():
            roles = exp.dis.vertex(src).layer.param_roles()
            for pname, v in exp.dis_state.params[src].items():
                if roles.get(pname) == "state":
                    continue
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(exp.cv_state.params[dst][pname])
                )
        # gan tail was synced BEFORE the gan step; the frozen tail's LR is 0
        # so weights stayed equal, but its BN running stats advanced during
        # the gan step — weights equal, stats differ (SURVEY §7 hard parts)
        for src, dst in DIS_TO_GAN.items():
            roles = exp.dis.vertex(src).layer.param_roles()
            for pname, v in exp.dis_state.params[src].items():
                if roles.get(pname) == "state":
                    continue
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(exp.gan_state.params[dst][pname])
                )

    def test_label_noise_reference_quirk(self, tmp_path):
        cfg = tiny_config(tmp_path)
        exp = GanExperiment(cfg)
        eps1 = exp._eps_real.copy()
        exp.train_iteration(*_one_batch())
        np.testing.assert_array_equal(exp._eps_real, eps1)  # sampled once, reused

    @pytest.mark.slow
    def test_label_noise_oversized_batch(self, tmp_path):
        """A batch larger than batch_size_train must extend the once-sampled
        noise, not silently truncate it (round-1 VERDICT weak #6)."""
        cfg = tiny_config(tmp_path, save_models=False)
        exp = GanExperiment(cfg)  # batch_size_train=16
        assert exp._eps_real.shape[0] == 16
        losses = exp.train_iteration(*_one_batch(24))
        assert np.isfinite(float(losses["d_loss"]))
        assert exp._eps_real.shape[0] == 24
        prefix = exp._eps_real[:16].copy()
        # the original 16 rows are preserved; shrinking back also works and
        # the cache entry for the smaller batch is consistent
        losses = exp.train_iteration(*_one_batch(16))
        assert np.isfinite(float(losses["d_loss"]))
        np.testing.assert_array_equal(exp._eps_real[:16], prefix)

    @pytest.mark.slow
    def test_bf16_compute_dtype_parity(self, tmp_path):
        """Mixed precision (VERDICT weak #3): bf16 matmul/conv with f32
        accumulation must stay numerically close to the f32 run and keep
        params in f32."""
        import jax

        x, y = _one_batch()
        runs = {}
        for dt in (None, "bf16"):
            cfg = tiny_config(tmp_path, save_models=False, compute_dtype=dt)
            exp = GanExperiment(cfg)
            losses = exp.train_iteration(x, y)
            runs[dt] = {k: float(v) for k, v in losses.items()}
            # params remain f32 regardless of compute dtype
            leaves = jax.tree_util.tree_leaves(exp.dis_state.params)
            assert all(l.dtype == np.float32 for l in leaves)
        for k in ("d_loss", "g_loss", "cv_loss"):
            assert np.isfinite(runs["bf16"][k])
            # same-seed inits: first-step losses agree to bf16 resolution
            np.testing.assert_allclose(runs["bf16"][k], runs[None][k], rtol=0.05)

    def test_bad_compute_dtype_rejected(self):
        with pytest.raises(ValueError):
            ExperimentConfig(compute_dtype="fp8").validate()

    @pytest.mark.slow
    def test_bf16_param_storage(self, tmp_path):
        """param_dtype="bf16" (round-4 VERDICT item 3): params AND updater
        state live in bfloat16 end to end — the pure-bf16 storage mode for
        the bandwidth-bound regime — and training still converges."""
        import jax

        cfg = tiny_config(tmp_path, save_models=False, param_dtype="bf16")
        assert cfg.compute_dtype == "bf16"  # storage implies compute
        exp = GanExperiment(cfg)
        for state in (exp.dis_state, exp.gan_state, exp.cv_state):
            for leaf in jax.tree_util.tree_leaves((state.params, state.opt_state)):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    assert leaf.dtype == jnp.bfloat16
                else:
                    assert leaf.dtype == jnp.int32  # step counters stay int
        x, y = _one_batch()
        first = None
        for _ in range(6):
            losses = exp.train_iteration(x, y)
            if first is None:
                first = float(losses["cv_loss"])
        # params stay bf16 THROUGH the jitted step (no silent f32 upcast)
        for leaf in jax.tree_util.tree_leaves(exp.dis_state.params):
            assert leaf.dtype == jnp.bfloat16
        assert np.isfinite(float(losses["d_loss"]))
        # same batch 6x: the classifier must learn it (convergence guard)
        assert float(losses["cv_loss"]) < first

    @pytest.mark.slow
    def test_bf16_param_storage_checkpoint_roundtrip(self, tmp_path):
        """Save/resume under bf16 storage: dtype survives the zip round trip
        (npz stores bf16 as tagged uint16 bit patterns)."""
        import jax

        cfg = tiny_config(tmp_path, param_dtype="bf16", save_models=True,
                          num_iterations=1)
        exp = GanExperiment(cfg)
        x, y = _one_batch()
        exp.train_iteration(x, y)
        exp.save_models()
        exp2 = GanExperiment(cfg)
        exp2.load_models()
        for a, b in zip(
            jax.tree_util.tree_leaves(exp.dis_state.params),
            jax.tree_util.tree_leaves(exp2.dis_state.params),
        ):
            assert b.dtype == a.dtype == jnp.bfloat16
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_eval_callback_fires_at_export_boundaries(self, tmp_path):
        """run(eval_callback=...) must fire exactly at the print_every
        cadence with the model state current (the best-checkpoint selection
        hook scripts/quality_run.py builds on) — including through the
        windowed device-loop path."""
        cfg = tiny_config(
            tmp_path, num_iterations=4, print_every=2, save_models=False,
            loss_fetch_every=4,
        )
        exp = GanExperiment(cfg)
        train, _ = iterators()
        seen = []

        def cb(e, index):
            assert e is exp
            # state is current: the gan step counter equals the iterations
            # completed at this boundary (batch_counter + the one just run)
            seen.append((index, int(e.gan_state.step)))

        result = exp.run(train, eval_callback=cb)
        assert result["iterations"] == 4
        assert seen == [(1, 1), (3, 3)]  # batch_counter 0 and 2

    @pytest.mark.slow
    def test_distributed_pmean_mode(self, tmp_path):
        cfg = tiny_config(tmp_path, distributed="pmean", save_models=False, num_iterations=1)
        exp = GanExperiment(cfg)
        train, _ = iterators()
        result = exp.run(train)
        assert result["iterations"] == 1
        assert np.isfinite(result["history"][0]["d_loss"])


def _one_batch(n=16):
    (xtr, ytr), _ = synthetic_mnist(n, 1)
    return xtr, one_hot_np(ytr, 10)


class TestFamilies:
    """The generalized harness: the alternating loop over non-MNIST families."""

    def test_tabular_family_iteration(self):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

        cfg = ExperimentConfig(
            model_family="tabular", num_features=16, z_size=4,
            batch_size_train=8, batch_size_pred=8, num_iterations=1,
            save_models=False, height=1, width=1, channels=1,
        )
        exp = GanExperiment(cfg)
        assert exp.cv is None and exp.cv_trainer is None
        feats = exp.family.synthetic_data(8, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(8) % 10]
        losses = exp.train_iteration(feats, labels)
        assert np.isfinite(float(losses["d_loss"]))
        assert np.isfinite(float(losses["g_loss"]))
        assert np.isnan(float(losses["cv_loss"]))  # no classifier
        # save_models writes 3 zips, predictions export refuses
        with pytest.raises(ValueError):
            exp.export_predictions(None, 1)

    @pytest.mark.slow
    def test_image_family_iteration(self):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

        cfg = ExperimentConfig(
            model_family="cifar10", height=8, width=8, channels=3,
            num_features=192, z_size=4, batch_size_train=4, batch_size_pred=4,
            num_iterations=1, save_models=False,
        )
        exp = GanExperiment(cfg)
        feats = exp.family.synthetic_data(4, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(4) % 10]
        losses = exp.train_iteration(feats, labels)
        assert np.isfinite(float(losses["d_loss"]))
        assert np.isfinite(float(losses["g_loss"]))

    def test_unknown_family_rejected(self):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig

        with pytest.raises(KeyError):
            ExperimentConfig(model_family="bogus").validate()


class TestCheckpointCadence:
    """checkpoint_every: configurable per-iteration checkpoint interval
    (default 1 = the reference's every-iteration cadence)."""

    def _tabular_cfg(self, **overrides):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig

        base = dict(
            model_family="tabular", num_features=16, z_size=4,
            batch_size_train=8, batch_size_pred=8,
            height=1, width=1, channels=1, save_models=True,
        )
        base.update(overrides)
        return ExperimentConfig(**base)

    def test_validation(self):
        with pytest.raises(ValueError, match="checkpoint_every"):
            self._tabular_cfg(checkpoint_every=0).validate()

    def test_checkpoint_every_gates_saves(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        cfg = self._tabular_cfg(
            num_iterations=4, checkpoint_every=2,
            output_dir=str(tmp_path / "out"),
        )
        exp = GanExperiment(cfg)
        saved = []
        exp.save_models = lambda: saved.append(exp.batch_counter)
        feats = exp.family.synthetic_data(32, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(32) % 10]
        it = ArrayDataSetIterator(feats, labels, batch_size=8)
        exp.run(it)
        # reference cadence is every iteration; every-2 halves the
        # checkpoint IO while the boundary iterations still save — and the
        # run ends with a final-state save (iteration 3 is off-cadence, so
        # without it resume/publish would see weights 1 iteration stale)
        assert saved == [0, 2, 4]

    def test_window_limit_respects_checkpoint_cadence(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        common = dict(
            num_iterations=32, loss_fetch_every=8,
            print_every=4, save_every=4, output_dir=str(tmp_path / "o"),
        )
        # per-iteration checkpointing pins the device loop to windows of 1
        exp = GanExperiment(self._tabular_cfg(checkpoint_every=1, **common))
        exp.batch_counter = 1
        assert exp._window_limit(False) == 1
        # a sparser cadence re-opens the window up to its boundary
        exp4 = GanExperiment(self._tabular_cfg(checkpoint_every=4, **common))
        exp4.batch_counter = 1
        assert exp4._window_limit(False) == 4
        exp4.batch_counter = 4  # at a boundary: the state must be current
        assert exp4._window_limit(False) == 1


class TestEpilogueHook:
    """run(epilogue_callback=...): fires after every iteration's epilogue
    with current state (windows pinned to 1); False stops the loop cleanly
    — the supervision/preemption entry point."""

    def _cfg(self, tmp_path, **overrides):
        base = dict(
            model_family="tabular", num_features=16, z_size=4,
            batch_size_train=8, batch_size_pred=8,
            height=1, width=1, channels=1, save_models=False,
            output_dir=str(tmp_path / "out"),
        )
        base.update(overrides)
        return ExperimentConfig(**base)

    def test_fires_every_iteration_with_current_state(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        cfg = self._cfg(tmp_path, num_iterations=5, loss_fetch_every=8)
        exp = GanExperiment(cfg)
        feats = exp.family.synthetic_data(40, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(40) % 10]
        seen = []

        def hook(e, index):
            # the gan step counter must be current at every call (windows
            # collapse to 1 while a hook is active) AND consistent with
            # batch_counter — a publishing hook labels checkpoints with it
            seen.append((index, int(e.gan_state.step)))
            assert e.batch_counter == index

        it = ArrayDataSetIterator(feats, labels, batch_size=8)
        result = exp.run(it, epilogue_callback=hook)
        assert result["iterations"] == 5
        assert seen == [(i + 1, i + 1) for i in range(5)]

    def test_false_return_stops_cleanly(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        cfg = self._cfg(tmp_path, num_iterations=10)
        exp = GanExperiment(cfg)
        feats = exp.family.synthetic_data(80, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(80) % 10]
        it = ArrayDataSetIterator(feats, labels, batch_size=8)
        result = exp.run(
            it, epilogue_callback=lambda e, index: index < 3)
        # the hook returned False at index 3: that iteration completes
        # (and is counted/logged), nothing after it runs
        assert result["iterations"] == 3
        assert exp.batch_counter == 3
        assert len(result["history"]) == 3


class TestResume:
    @pytest.mark.slow
    def test_save_then_load_roundtrip(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

        cfg = ExperimentConfig(
            batch_size_train=8, batch_size_pred=8, num_iterations=1,
            output_dir=str(tmp_path), save_models=True,
        )
        exp = GanExperiment(cfg)
        (x, y_int), _ = synthetic_mnist(num_train=8, num_test=1, seed=0)
        y = one_hot_np(y_int, 10)
        exp.train_iteration(x, y)
        exp.save_models()

        exp2 = GanExperiment(cfg)
        restored = exp2.load_models()
        assert restored == int(exp.gan_state.step)
        import jax

        def assert_tree_equal(t1, t2):
            jax.tree_util.tree_map(
                lambda u, v: np.testing.assert_array_equal(np.asarray(u), np.asarray(v)),
                t1, t2,
            )

        assert_tree_equal(exp.dis_state.params, exp2.dis_state.params)
        assert_tree_equal(exp.dis_state.opt_state, exp2.dis_state.opt_state)
        assert_tree_equal(exp.gan_state.params, exp2.gan_state.params)
        assert_tree_equal(exp.cv_state.params, exp2.cv_state.params)
        assert_tree_equal(exp.gen_params, exp2.gen_params)

        # resumed training proceeds from the restored counter
        exp2.train_iteration(x, y)
        assert int(exp2.gan_state.step) == restored + 1
