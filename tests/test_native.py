"""Native C++ CSV layer: build, parity vs numpy, error paths, fallback."""

import numpy as np
import pytest

from gan_deeplearning4j_tpu.data.records import CSVRecordReader, FileSplit, write_csv
from gan_deeplearning4j_tpu.native import build, csv_loader

pytestmark = pytest.mark.skipif(
    not csv_loader.available(), reason="native toolchain unavailable"
)


class TestNativeRead:
    def test_parity_with_numpy(self, tmp_path):
        rng = np.random.default_rng(0)
        arr = (rng.random((50, 17)) * 200 - 100).astype(np.float32)
        p = tmp_path / "a.csv"
        np.savetxt(p, arr, delimiter=",", fmt="%.6f")
        native = csv_loader.load_csv(str(p))
        ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_array_equal(native, ref)

    def test_exponent_nan_inf_and_integers(self, tmp_path):
        p = tmp_path / "b.csv"
        p.write_text("1e-3,2.5E2,-4,nan,inf,-inf,0,666\n")
        out = csv_loader.load_csv(str(p))
        assert out.shape == (1, 8)
        np.testing.assert_allclose(out[0, :3], [1e-3, 250.0, -4.0])
        assert np.isnan(out[0, 3])
        assert np.isposinf(out[0, 4]) and np.isneginf(out[0, 5])
        assert out[0, 6] == 0.0 and out[0, 7] == 666.0

    def test_skip_lines_crlf_and_trailing_newline(self, tmp_path):
        p = tmp_path / "c.csv"
        p.write_text("header,line\r\n1.5,2.5\r\n3.5,4.5\n\n")
        out = csv_loader.load_csv(str(p), skip_lines=1)
        np.testing.assert_array_equal(out, [[1.5, 2.5], [3.5, 4.5]])

    def test_ragged_rows_rejected(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("1,2,3\n4,5\n")
        with pytest.raises(ValueError, match="ragged"):
            csv_loader.load_csv(str(p))

    def test_non_numeric_rejected(self, tmp_path):
        p = tmp_path / "e.csv"
        p.write_text("1,2\n3,abc\n")
        with pytest.raises(ValueError, match="parse"):
            csv_loader.load_csv(str(p))

    def test_empty_rejected(self, tmp_path):
        p = tmp_path / "f.csv"
        p.write_text("")
        with pytest.raises(ValueError, match="empty"):
            csv_loader.load_csv(str(p))

    def test_missing_file(self, tmp_path):
        with pytest.raises(ValueError, match="open"):
            csv_loader.load_csv(str(tmp_path / "nope.csv"))


class TestNativeWrite:
    def test_roundtrip_and_format_parity(self, tmp_path):
        rng = np.random.default_rng(1)
        arr = (rng.random((40, 7)) * 2000 - 1000).astype(np.float32)
        p_nat = tmp_path / "n.csv"
        p_np = tmp_path / "p.csv"
        csv_loader.write_csv(str(p_nat), arr, precision=4)
        np.savetxt(p_np, arr, delimiter=",", fmt="%.4f")
        a = np.loadtxt(p_nat, delimiter=",", ndmin=2)
        b = np.loadtxt(p_np, delimiter=",", ndmin=2)
        # same values to within the last printed digit (tie-breaking at the
        # half-ulp boundary may differ from printf's)
        np.testing.assert_allclose(a, b, atol=1.01e-4)

    def test_special_values(self, tmp_path):
        arr = np.array([[np.nan, np.inf, -np.inf, 1e20, -0.0]], np.float32)
        p = tmp_path / "s.csv"
        csv_loader.write_csv(str(p), arr, precision=2)
        txt = p.read_text()
        assert "nan" in txt and "inf" in txt
        back = csv_loader.load_csv(str(p))
        assert np.isnan(back[0, 0]) and np.isposinf(back[0, 1])

    def test_bad_shape_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="2-D"):
            csv_loader.write_csv(str(tmp_path / "x.csv"), np.zeros(3))


class TestIntegration:
    def test_record_reader_uses_native(self, tmp_path):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0
        p = tmp_path / "r.csv"
        write_csv(str(p), arr, precision=6)
        reader = CSVRecordReader(0, ",")
        reader.initialize(FileSplit(str(p)))
        np.testing.assert_allclose(reader.data, arr, atol=1e-6)

    def test_write_csv_fallback(self, tmp_path, monkeypatch):
        # when the native lib is unavailable the numpy path produces the file
        monkeypatch.setattr(csv_loader, "available", lambda: False)
        arr = np.ones((2, 2), np.float32) / 3.0
        p = tmp_path / "f.csv"
        write_csv(str(p), arr, precision=3)
        np.testing.assert_allclose(
            np.loadtxt(p, delimiter=",", ndmin=2), np.full((2, 2), 0.333), atol=1e-9
        )

    def test_rebuild_is_cached(self):
        path = build.build()
        assert path is not None
        assert not build.needs_build()

    def test_large_values_not_corrupted(self, tmp_path):
        # regression: v * 10^precision overflowing uint64 must take the
        # printf path, not silently emit zeros
        arr = np.array([[1e14, 2e13, 3.4e38, -1.5e16]], np.float32)
        p = tmp_path / "big.csv"
        csv_loader.write_csv(str(p), arr, precision=6)
        back = csv_loader.load_csv(str(p))
        np.testing.assert_allclose(back, arr, rtol=1e-6)
        assert "\x00" not in p.read_text()

    def test_max_float_high_precision_no_nul_bytes(self, tmp_path):
        arr = np.full((300, 4), np.finfo(np.float32).max, np.float32)
        p = tmp_path / "max.csv"
        csv_loader.write_csv(str(p), arr, precision=17)
        txt = p.read_text()
        assert "\x00" not in txt
        back = csv_loader.load_csv(str(p))
        np.testing.assert_allclose(back, arr, rtol=1e-6)
