"""Op-layer tests: analytic values, shape parity with DL4J Truncate mode, and
finite-difference gradient checks (SURVEY §4's prescribed test pyramid)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.ops import activations, clipping, conv, initializers, linear, losses, norm


def fd_grad(f, x, eps=1e-4):
    """Central finite differences of scalar f at x."""
    x = np.asarray(x, dtype=np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(jnp.asarray(xp, jnp.float32)) - f(jnp.asarray(xm, jnp.float32))) / (2 * eps)
        it.iternext()
    return g


class TestActivations:
    def test_values(self):
        x = jnp.array([-1.0, 0.0, 1.0])
        np.testing.assert_allclose(activations.tanh(x), np.tanh([-1, 0, 1]), atol=1e-6)
        np.testing.assert_allclose(
            activations.sigmoid(x), 1 / (1 + np.exp([1.0, 0.0, -1.0])), atol=1e-6
        )
        s = activations.softmax(jnp.array([[1.0, 2.0, 3.0]]))
        np.testing.assert_allclose(np.sum(np.asarray(s)), 1.0, atol=1e-6)

    def test_registry(self):
        assert activations.get("TANH") is activations.tanh
        assert activations.get(activations.relu) is activations.relu
        with pytest.raises(KeyError):
            activations.get("nope")


class TestDense:
    def test_matmul_bias(self):
        x = jnp.array([[1.0, 2.0]])
        w = jnp.array([[1.0, 0.0, 1.0], [0.0, 1.0, 1.0]])
        b = jnp.array([0.5, 0.5, 0.5])
        y = linear.dense(x, w, b)
        np.testing.assert_allclose(np.asarray(y), [[1.5, 2.5, 3.5]], atol=1e-6)


class TestConv:
    def test_out_size_matches_reference_dis(self):
        # dis topology (dl4jGANComputerVision.java:136-154): 28 -> conv5 s2 -> 12
        # -> pool2 s1 -> 11 -> conv5 s2 -> 4 -> pool2 s1 -> 3
        assert conv.conv_out_size(28, 5, 2, 0) == 12
        assert conv.conv_out_size(12, 2, 1, 0) == 11
        assert conv.conv_out_size(11, 5, 2, 0) == 4
        assert conv.conv_out_size(4, 2, 1, 0) == 3

    def test_conv2d_identity_kernel(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        w = jnp.zeros((3, 3, 1, 1)).at[1, 1, 0, 0].set(1.0)
        y = conv.conv2d(x, w, stride=1, padding=1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)

    def test_conv2d_shapes(self):
        x = jnp.ones((2, 28, 28, 1))
        w = jnp.ones((5, 5, 1, 64))
        y = conv.conv2d(x, w, stride=2, padding=0)
        assert y.shape == (2, 12, 12, 64)
        # generator conv: 5x5 s1 p2 preserves spatial dims (:207-213)
        x2 = jnp.ones((2, 14, 14, 128))
        w2 = jnp.ones((5, 5, 128, 64))
        assert conv.conv2d(x2, w2, stride=1, padding=2).shape == (2, 14, 14, 64)

    def test_conv2d_vs_manual(self):
        k = jax.random.PRNGKey(0)
        x = jax.random.normal(k, (1, 5, 5, 2))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 2, 3))
        y = conv.conv2d(x, w, stride=1, padding=0)
        xn, wn = np.asarray(x), np.asarray(w)
        expect = np.zeros((1, 3, 3, 3))
        for i in range(3):
            for j in range(3):
                patch = xn[0, i : i + 3, j : j + 3, :]
                for o in range(3):
                    expect[0, i, j, o] = np.sum(patch * wn[:, :, :, o])
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-4)

    def test_conv_transpose_shape(self):
        x = jnp.ones((2, 7, 7, 128))
        w = jnp.ones((4, 4, 128, 64))
        y = conv.conv2d_transpose(x, w, stride=2, padding=1)
        assert y.shape == (2, 14, 14, 64)  # (7-1)*2 - 2 + 4 = 14

    def test_max_pool(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y = conv.max_pool2d(x, kernel=2, stride=1)
        assert y.shape == (1, 3, 3, 1)
        assert float(y[0, 0, 0, 0]) == 5.0  # max of [[0,1],[4,5]]
        assert float(y[0, 2, 2, 0]) == 15.0

    def test_avg_pool(self):
        x = jnp.arange(4.0).reshape(1, 2, 2, 1)
        y = conv.avg_pool2d(x, kernel=2, stride=1)
        np.testing.assert_allclose(float(y[0, 0, 0, 0]), 1.5)

    def test_upsample(self):
        x = jnp.array([[1.0, 2.0], [3.0, 4.0]]).reshape(1, 2, 2, 1)
        y = conv.upsample2d(x, scale=2)
        assert y.shape == (1, 4, 4, 1)
        np.testing.assert_allclose(
            np.asarray(y[0, :, :, 0]),
            [[1, 1, 2, 2], [1, 1, 2, 2], [3, 3, 4, 4], [3, 3, 4, 4]],
        )


class TestBatchNorm:
    def test_train_normalizes(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (64, 8)) * 3.0 + 5.0
        gamma, beta = jnp.ones(8), jnp.zeros(8)
        rm, rv = jnp.zeros(8), jnp.ones(8)
        y, nm, nv = norm.batch_norm_train(x, gamma, beta, rm, rv)
        np.testing.assert_allclose(np.asarray(jnp.mean(y, 0)), np.zeros(8), atol=1e-5)
        np.testing.assert_allclose(np.asarray(jnp.std(y, 0)), np.ones(8), atol=1e-2)
        # running stats moved toward batch stats with decay 0.9
        np.testing.assert_allclose(np.asarray(nm), 0.1 * np.asarray(jnp.mean(x, 0)), atol=1e-5)

    def test_inference_uses_running_stats(self):
        x = jnp.ones((4, 3)) * 2.0
        y = norm.batch_norm_inference(
            x, jnp.ones(3), jnp.zeros(3), jnp.ones(3) * 2.0, jnp.ones(3)
        )
        np.testing.assert_allclose(np.asarray(y), np.zeros((4, 3)), atol=1e-3)

    def test_nhwc_reduction_axes(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, 6, 4)) * 2 + 1
        y, _, _ = norm.batch_norm_train(x, jnp.ones(4), jnp.zeros(4), jnp.zeros(4), jnp.ones(4))
        m = np.asarray(jnp.mean(y, axis=(0, 1, 2)))
        np.testing.assert_allclose(m, np.zeros(4), atol=1e-5)


class TestLosses:
    def test_binary_xent_analytic(self):
        p = jnp.array([[0.9], [0.1]])
        t = jnp.array([[1.0], [0.0]])
        expect = -np.mean([np.log(0.9), np.log(0.9)])
        np.testing.assert_allclose(float(losses.binary_xent(p, t)), expect, atol=1e-5)

    def test_binary_xent_clips(self):
        p = jnp.array([[0.0], [1.0]])
        t = jnp.array([[1.0], [0.0]])
        v = float(losses.binary_xent(p, t))
        assert np.isfinite(v)
        np.testing.assert_allclose(v, -np.log(1e-5), rtol=1e-4)

    def test_categorical_xent(self):
        p = jnp.array([[0.7, 0.2, 0.1]])
        t = jnp.array([[1.0, 0.0, 0.0]])
        np.testing.assert_allclose(float(losses.categorical_xent(p, t)), -np.log(0.7), atol=1e-5)

    def test_wasserstein(self):
        scores = jnp.array([2.0, -1.0])
        labels = jnp.array([1.0, -1.0])
        np.testing.assert_allclose(float(losses.wasserstein(scores, labels)), -1.5)

    def test_gradient_penalty_zero_for_unit_grad(self):
        # critic(x) = sum(x) has gradient exactly 1 per element; with 1-d x the
        # norm is 1 so the penalty vanishes.
        real = jnp.ones((8, 1))
        fake = jnp.zeros((8, 1))
        gp = losses.gradient_penalty(
            lambda x: jnp.sum(x, axis=1), real, fake, jax.random.PRNGKey(0)
        )
        np.testing.assert_allclose(float(gp), 0.0, atol=1e-6)

    def test_gradient_penalty_grad_of_grad(self):
        # differentiating through the penalty (grad-of-grad) must work
        w = jnp.array(2.0)
        real = jnp.ones((4, 3))
        fake = jnp.zeros((4, 3))

        def outer(w):
            return losses.gradient_penalty(
                lambda x: w * jnp.sum(x, axis=(1,)), real, fake, jax.random.PRNGKey(1)
            )

        g = jax.grad(outer)(w)
        assert np.isfinite(float(g)) and abs(float(g)) > 0


class TestGradients:
    """Finite-difference checks of op gradients."""

    def test_dense_grad(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 4))
        w0 = jax.random.normal(jax.random.PRNGKey(1), (4, 2))

        def f(w):
            return jnp.sum(jnp.tanh(linear.dense(x, w)))

        g = jax.grad(f)(w0)
        g_fd = fd_grad(lambda w: float(f(w)), w0)
        np.testing.assert_allclose(np.asarray(g), g_fd, atol=1e-2)

    def test_conv_grad(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 5, 5, 1))
        w0 = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 1, 2)) * 0.5

        def f(w):
            return jnp.sum(conv.conv2d(x, w, stride=1, padding=1) ** 2)

        g = jax.grad(f)(w0)
        g_fd = fd_grad(lambda w: float(f(w)), w0)
        np.testing.assert_allclose(np.asarray(g), g_fd, atol=1e-1, rtol=1e-2)

    def test_bn_grad(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 3))
        g0 = jnp.ones(3)

        def f(gamma):
            y, _, _ = norm.batch_norm_train(x, gamma, jnp.zeros(3), jnp.zeros(3), jnp.ones(3))
            return jnp.sum(y**2)

        g = jax.grad(f)(g0)
        g_fd = fd_grad(lambda gm: float(f(gm)), g0)
        np.testing.assert_allclose(np.asarray(g), g_fd, atol=1e-2, rtol=1e-2)


class TestClipping:
    def test_elementwise(self):
        grads = {"a": jnp.array([-5.0, 0.5, 3.0])}
        out = clipping.clip_elementwise(grads, 1.0)
        np.testing.assert_allclose(np.asarray(out["a"]), [-1.0, 0.5, 1.0])

    def test_global_norm(self):
        grads = {"a": jnp.array([3.0, 4.0])}  # norm 5
        out = clipping.clip_by_global_norm(grads, 1.0)
        np.testing.assert_allclose(np.asarray(out["a"]), [0.6, 0.8], atol=1e-5)


class TestInitializers:
    def test_xavier_stats(self):
        w = initializers.xavier(jax.random.PRNGKey(0), (1000, 500))
        expect_std = np.sqrt(2.0 / 1500)
        assert abs(float(jnp.std(w)) - expect_std) < 0.05 * expect_std
        assert abs(float(jnp.mean(w))) < 1e-2

    def test_conv_fans(self):
        # HWIO (5,5,1,64): fan_in = 25, fan_out = 1600
        w = initializers.xavier(jax.random.PRNGKey(0), (5, 5, 1, 64))
        expect_std = np.sqrt(2.0 / (25 + 1600))
        assert abs(float(jnp.std(w)) - expect_std) < 0.1 * expect_std

    def test_registry(self):
        assert initializers.get("XAVIER") is initializers.xavier
        with pytest.raises(KeyError):
            initializers.get("bogus")
