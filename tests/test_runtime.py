"""Runtime core tests: dtype policy, PRNG streams, array factory, environment."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.runtime import dtype as dtype_mod
from gan_deeplearning4j_tpu.runtime import factory
from gan_deeplearning4j_tpu.runtime.environment import TpuEnvironment, backend_info
from gan_deeplearning4j_tpu.runtime.prng import RngStream


class TestDtypePolicy:
    def test_default_is_float32(self):
        assert dtype_mod.get_default_dtype() == jnp.float32

    def test_scope(self):
        with dtype_mod.default_dtype_scope(jnp.bfloat16):
            assert dtype_mod.get_default_dtype() == jnp.bfloat16
            assert factory.zeros(2, 2).dtype == jnp.bfloat16
        assert dtype_mod.get_default_dtype() == jnp.float32

    def test_compute_dtype_scope(self):
        assert dtype_mod.get_compute_dtype() == jnp.float32
        with dtype_mod.compute_dtype_scope(jnp.bfloat16):
            assert dtype_mod.get_compute_dtype() == jnp.bfloat16


class TestRngStream:
    def test_deterministic(self):
        a = RngStream(666)
        b = RngStream(666)
        assert jnp.array_equal(a.next_key(), b.next_key())
        assert jnp.array_equal(a.next_key(), b.next_key())

    def test_keys_differ(self):
        s = RngStream(666)
        k1, k2 = s.next_key(), s.next_key()
        assert not jnp.array_equal(k1, k2)

    def test_reset(self):
        s = RngStream(1)
        k1 = s.next_key()
        s.reset()
        assert jnp.array_equal(k1, s.next_key())


class TestFactory:
    def test_randn_shape_dtype(self, rng):
        x = factory.randn(rng, 3, 4)
        assert x.shape == (3, 4) and x.dtype == jnp.float32

    def test_rand_range(self, rng):
        x = factory.rand(rng, 1000)
        assert float(x.min()) >= 0.0 and float(x.max()) < 1.0

    def test_uniform_latent_range(self, rng):
        z = factory.uniform_latent(rng, 200, 2)
        assert z.shape == (200, 2)
        assert float(z.min()) >= -1.0 and float(z.max()) < 1.0

    def test_stream_accepted(self):
        s = RngStream(666)
        x = factory.randn(s, 2, 2)
        y = factory.randn(s, 2, 2)
        assert not jnp.array_equal(x, y)

    def test_linspace_vstack_create(self):
        ls = factory.linspace(-1.0, 1.0, 10)
        assert ls.shape == (10,) and np.isclose(float(ls[0]), -1) and np.isclose(float(ls[-1]), 1)
        v = factory.vstack([factory.ones(2, 3), factory.zeros(1, 3)])
        assert v.shape == (3, 3)
        c = factory.create([[1, 2], [3, 4]])
        assert c.dtype == jnp.float32

    def test_latent_grid(self):
        # The reference's 10x10 manifold grid (dl4jGANComputerVision.java:382-389)
        g = factory.latent_grid(10)
        assert g.shape == (100, 2)
        np.testing.assert_allclose(factory.to_host(g[0]), [-1, -1], atol=1e-6)
        np.testing.assert_allclose(factory.to_host(g[-1]), [1, 1], atol=1e-6)
        # rows iterate the second coordinate fastest
        np.testing.assert_allclose(factory.to_host(g[1]), [-1, -1 + 2 / 9], atol=1e-6)


class TestEnvironment:
    def test_backend_info(self):
        info = backend_info()
        assert info["device_count"] >= 1
        assert info["platform"] in ("cpu", "tpu", "axon", "gpu")

    def test_fake_mesh_has_8_devices(self):
        # conftest forces 8 virtual CPU devices (SURVEY §4: local[4] analog)
        assert len(jax.devices()) == 8

    def test_make_mesh(self):
        env = TpuEnvironment()
        mesh = env.make_mesh()
        assert mesh.axis_names == ("data",)
        assert mesh.devices.size == 8

    def test_device_limit(self):
        env = TpuEnvironment(device_limit=4)
        assert env.device_count() == 4
        mesh = env.make_mesh()
        assert mesh.devices.size == 4

    def test_multi_axis_mesh(self):
        env = TpuEnvironment(mesh_axes=("data", "model"))
        mesh = env.make_mesh(axis_sizes=[4, 2])
        assert mesh.shape == {"data": 4, "model": 2}

    def test_bad_axis_sizes_raise(self):
        env = TpuEnvironment(mesh_axes=("data",))
        with pytest.raises(ValueError):
            env.make_mesh(axis_sizes=[3])
