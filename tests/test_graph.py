"""Graph-system tests: shape inference, auto-preprocessors, named params,
summary, serialization, loss, and the DL4J config-inheritance behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    ActivationLayer,
    BatchNormalization,
    ComputationGraph,
    ConvolutionLayer,
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
    SubsamplingLayer,
    Upsampling2D,
)
from gan_deeplearning4j_tpu.nn.graph import MergeVertex
from gan_deeplearning4j_tpu.optim import RmsProp, Sgd


def small_mlp():
    b = GraphBuilder(GraphConfig(seed=7, default_activation="tanh", updater=Sgd(0.1)))
    b.add_inputs("in")
    b.set_input_types(InputType.feed_forward(4))
    b.add_layer("h", DenseLayer(n_out=8), "in")
    b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "h")
    b.set_outputs("out")
    return b.build()


class TestBuilder:
    def test_duplicate_name_raises(self):
        b = GraphBuilder()
        b.add_inputs("in")
        with pytest.raises(ValueError):
            b.add_inputs("in")
        b.add_layer("x", DenseLayer(n_out=2), "in")
        with pytest.raises(ValueError):
            b.add_layer("x", DenseLayer(n_out=2), "in")

    def test_missing_outputs_raise(self):
        b = GraphBuilder()
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("h", DenseLayer(n_out=2), "in")
        with pytest.raises(ValueError):
            b.build()

    def test_unknown_input_raises(self):
        b = GraphBuilder()
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("h", DenseLayer(n_out=2), "nope")
        b.set_outputs("h")
        with pytest.raises(ValueError, match="unresolvable"):
            b.build()

    def test_defaults_inherited_and_overridable(self):
        cfg = GraphConfig(default_activation="relu", l2=0.5, updater=Sgd(0.1))
        b = GraphBuilder(cfg)
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("a", DenseLayer(n_out=2), "in")
        b.add_layer("b", DenseLayer(n_out=2, activation="sigmoid", updater=RmsProp(0.9)), "a")
        b.set_outputs("b")
        g = b.build()
        la = g.vertex("a").layer
        lb = g.vertex("b").layer
        assert la.activation == "relu" and la.l2 == 0.5 and la.updater == Sgd(0.1)
        assert lb.activation == "sigmoid" and lb.updater == RmsProp(0.9)

    def test_batchnorm_default_activation_identity(self):
        # DL4J BN layers don't get the graph's tanh default applied after norm
        b = GraphBuilder(GraphConfig(default_activation="tanh"))
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("bn", BatchNormalization(), "in")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "bn")
        b.set_outputs("out")
        g = b.build()
        assert g.vertex("bn").layer.activation == "identity"


class TestShapeInference:
    def test_cnn_flat_to_conv_chain(self):
        # the reference dis topology shape walk: 28x28 -> 12 -> 11 -> 4 -> 3
        b = GraphBuilder(GraphConfig())
        b.add_inputs("in")
        b.set_input_types(InputType.convolutional_flat(28, 28, 1))
        b.add_layer("bn", BatchNormalization(), "in")
        b.add_layer("c1", ConvolutionLayer(kernel=5, stride=2, n_out=64), "bn")
        b.add_layer("p1", SubsamplingLayer(kernel=2, stride=1), "c1")
        b.add_layer("c2", ConvolutionLayer(kernel=5, stride=2, n_out=128), "p1")
        b.add_layer("p2", SubsamplingLayer(kernel=2, stride=1), "c2")
        b.add_layer("d", DenseLayer(n_out=1024), "p2")
        b.set_outputs("d")
        g = b.build()
        assert g.vertex("bn").out_type.shape == (28, 28, 1)
        assert g.vertex("c1").out_type.shape == (12, 12, 64)
        assert g.vertex("p1").out_type.shape == (11, 11, 64)
        assert g.vertex("c2").out_type.shape == (4, 4, 128)
        assert g.vertex("p2").out_type.shape == (3, 3, 128)
        assert g.vertex("d").in_type.features == 1152
        # BN on convolutionalFlat normalizes channels (DL4J CNNFlat), so 4 params of size 1
        params = g.init()
        assert params["bn"]["gamma"].shape == (1,)

    def test_upsample_shapes(self):
        b = GraphBuilder(GraphConfig())
        b.add_inputs("in")
        b.set_input_types(InputType.convolutional(7, 7, 128))
        b.add_layer("u", Upsampling2D(size=2), "in")
        b.set_outputs("u")
        g = b.build()
        assert g.vertex("u").out_type.shape == (14, 14, 128)

    def test_merge_vertex(self):
        b = GraphBuilder(GraphConfig())
        b.add_inputs("a", "b")
        b.set_input_types(InputType.feed_forward(3), InputType.feed_forward(5))
        b.add_vertex("m", MergeVertex(), "a", "b")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "m")
        b.set_outputs("out")
        g = b.build()
        assert g.vertex("m").out_type.shape == (8,)
        outs, _ = g.apply(g.init(), {"a": jnp.ones((2, 3)), "b": jnp.zeros((2, 5))})
        assert outs["out"].shape == (2, 2)


class TestApply:
    def test_forward_shapes_and_jit(self):
        g = small_mlp()
        params = g.init()
        x = jnp.ones((5, 4))
        y = g.output(params, x)
        assert y.shape == (5, 2)
        np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), np.ones(5), atol=1e-5)

        jitted = jax.jit(lambda p, x: g.output(p, x))
        np.testing.assert_allclose(np.asarray(jitted(params, x)), np.asarray(y), atol=1e-6)

    def test_deterministic_init(self):
        g = small_mlp()
        p1, p2 = g.init(), g.init()
        np.testing.assert_array_equal(np.asarray(p1["h"]["W"]), np.asarray(p2["h"]["W"]))
        p3 = g.init(seed=123)
        assert not np.array_equal(np.asarray(p1["h"]["W"]), np.asarray(p3["h"]["W"]))

    def test_bn_stats_update_only_in_train(self):
        b = GraphBuilder(GraphConfig())
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(3))
        b.add_layer("bn", BatchNormalization(), "in")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "bn")
        b.set_outputs("out")
        g = b.build()
        params = g.init()
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3)) + 4.0
        _, p_inf = g.apply(params, x, train=False)
        np.testing.assert_array_equal(np.asarray(p_inf["bn"]["mean"]), np.asarray(params["bn"]["mean"]))
        _, p_tr = g.apply(params, x, train=True)
        assert not np.array_equal(np.asarray(p_tr["bn"]["mean"]), np.asarray(params["bn"]["mean"]))
        # decay 0.9: new mean = 0.1 * batch mean
        np.testing.assert_allclose(
            np.asarray(p_tr["bn"]["mean"]), 0.1 * np.asarray(jnp.mean(x, 0)), atol=1e-5
        )

    def test_loss_includes_l2(self):
        cfg = GraphConfig(l2=0.01, updater=Sgd(0.1))
        b = GraphBuilder(cfg)
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "in")
        b.set_outputs("out")
        g = b.build()
        params = g.init()
        x = jnp.ones((3, 4))
        labels = jax.nn.one_hot(jnp.array([0, 1, 0]), 2)
        loss, _ = g.loss(params, x, labels)
        w = np.asarray(params["out"]["W"])
        expected_l2 = 0.5 * 0.01 * np.sum(w**2)
        # loss = xent + l2 term; recompute xent via output
        probs = np.asarray(g.output(params, x))
        xent = -np.mean(np.sum(np.asarray(labels) * np.log(np.clip(probs, 1e-5, 1)), -1))
        np.testing.assert_allclose(float(loss), xent + expected_l2, rtol=1e-5)


class TestNamedParams:
    def test_get_set(self):
        g = small_mlp()
        params = g.init()
        w = ComputationGraph.get_param(params, "h", "W")
        new = ComputationGraph.set_param(params, "h", "W", jnp.zeros_like(w))
        assert float(jnp.sum(jnp.abs(new["h"]["W"]))) == 0.0
        # original untouched (functional)
        assert float(jnp.sum(jnp.abs(params["h"]["W"]))) > 0.0

    def test_set_param_validates(self):
        g = small_mlp()
        params = g.init()
        with pytest.raises(KeyError):
            ComputationGraph.set_param(params, "nope", "W", jnp.zeros((1,)))
        with pytest.raises(KeyError):
            ComputationGraph.set_param(params, "h", "Q", jnp.zeros((1,)))
        with pytest.raises(ValueError):
            ComputationGraph.set_param(params, "h", "W", jnp.zeros((1, 1)))

    def test_copy_params(self):
        g = small_mlp()
        src, dst = g.init(seed=1), g.init(seed=2)
        out = ComputationGraph.copy_params(src, dst, {"h": "h"})
        np.testing.assert_array_equal(np.asarray(out["h"]["W"]), np.asarray(src["h"]["W"]))
        np.testing.assert_array_equal(np.asarray(out["out"]["W"]), np.asarray(dst["out"]["W"]))
        with pytest.raises(KeyError):
            ComputationGraph.copy_params(src, dst, {"h": "bogus"})


class TestSummarySerialization:
    def test_summary_contains_layers_and_total(self):
        g = small_mlp()
        s = g.summary()
        assert "h (DenseLayer)" in s and "out (OutputLayer)" in s
        assert f"Total params: {g.param_count()}" in s

    def test_dict_roundtrip(self):
        g = small_mlp()
        d = g.to_dict()
        import json

        g2 = ComputationGraph.from_dict(json.loads(json.dumps(d)))
        assert g2.summary() == g.summary()
        params = g.init()
        x = jnp.ones((2, 4))
        np.testing.assert_allclose(
            np.asarray(g2.output(params, x)), np.asarray(g.output(params, x)), atol=1e-6
        )

    def test_activation_layer(self):
        b = GraphBuilder(GraphConfig())
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(3))
        b.add_layer("act", ActivationLayer(activation="relu"), "in")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "act")
        b.set_outputs("out")
        g = b.build()
        y, _ = g.apply(g.init(), jnp.array([[-1.0, 2.0, -3.0]]))
        assert y["out"].shape == (1, 2)


class TestReviewRegressions:
    """Regressions for review findings on the graph/transfer/prng layer."""

    def test_copy_params_shape_mismatch_raises(self):
        a = {"x": {"W": jnp.zeros((3, 3))}}
        b = {"y": {"W": jnp.zeros((5, 5))}}
        with pytest.raises(ValueError, match="shape mismatch"):
            ComputationGraph.copy_params(a, b, {"x": "y"})

    def test_fork_reset_independent(self):
        from gan_deeplearning4j_tpu.runtime.prng import RngStream

        s = RngStream(7)
        first_parent_key = RngStream(7).next_key()
        c = s.fork()
        c.reset()
        assert not np.array_equal(np.asarray(c.next_key()), np.asarray(first_parent_key))

    def test_remove_mid_vertex_rewires(self):
        from gan_deeplearning4j_tpu.nn import TransferLearning

        b = GraphBuilder(GraphConfig(updater=Sgd(0.1)))
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("h1", DenseLayer(n_out=4), "in")
        b.add_layer("h2", ActivationLayer(activation="relu"), "h1")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "h2")
        b.set_outputs("out")
        g = b.build()
        params = g.init()
        g2, p2 = TransferLearning(g, params).remove_vertex_keep_connections("h2").build()
        # out now consumes h1 directly
        assert g2.vertex("out").inputs == ("h1",)
        y = g2.output(p2, jnp.ones((2, 4)))
        assert y.shape == (2, 2)

    def test_fine_tune_l2_applies_to_retained_layers(self):
        from gan_deeplearning4j_tpu.nn import FineTuneConfiguration, TransferLearning

        b = GraphBuilder(GraphConfig(l2=0.1, updater=Sgd(0.1)))
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(4))
        b.add_layer("h", DenseLayer(n_out=4), "in")
        b.add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "h")
        b.set_outputs("out")
        g = b.build()
        params = g.init()
        g2, p2 = (
            TransferLearning(g, params)
            .fine_tune_configuration(FineTuneConfiguration(l2=0.0))
            .build()
        )
        assert float(g2.l2_penalty(p2)) == 0.0
        assert g2.vertex("h").layer.l2 == 0.0
