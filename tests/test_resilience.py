"""resilience/ tests: the generation-ledgered store (publish atomicity,
digest verification, quarantine, retention GC), the deterministic fault
plane, the supervisor's resume/preemption/backoff contract — and the CPU
drill smoke, which kills a real training process at step N and proves
bit-exact recovery end to end."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from gan_deeplearning4j_tpu.resilience import (
    CheckpointStore,
    FaultInjector,
    FaultSchedule,
    FaultSpec,
    InjectedFault,
    MeshCoordinator,
    MeshTimeout,
    RetryBudgetExceeded,
    SupervisorConfig,
    TrainingSupervisor,
    UnsupportedExperimentError,
    corrupt_generation,
    mesh_digest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compile_cache():
    """The XLA:CPU persistent compilation cache's AOT loader is unsafe on
    CPU (runtime/environment.py documents cpu_aot_loader errors and SIGILL
    risk; the suite opts in anyway for warm-start speed). This module
    serially builds and tears down MANY identical fused programs — the
    write-then-load-in-process pattern that reliably turns the hazard into
    glibc heap corruption ('corrupted double-linked list' → segfault,
    reproduced on the seed image). Run the module with the persistent
    cache off; jax memoizes the cache-used decision, so reset it on both
    edges."""
    jax = pytest.importorskip("jax")
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()  # drop the memoized "cache is used" decision
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    _cc.reset_cache()


def write_files(payload):
    """A store writer callback that writes a dict of name -> bytes."""
    def writer(directory):
        for name, data in payload.items():
            with open(os.path.join(directory, name), "wb") as fh:
                fh.write(data)
    return writer


# ===========================================================================
# CheckpointStore
# ===========================================================================

class TestStore:
    def test_publish_and_latest_round_trip(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        g = store.publish(write_files({"a.bin": b"alpha", "b.bin": b"beta"}),
                          step=7, extra={"state_digests": {"a": "x"}})
        assert g.number == 0 and g.step == 7
        latest = store.latest_valid()
        assert latest is not None and latest.number == 0
        assert open(latest.file("a.bin"), "rb").read() == b"alpha"
        assert latest.manifest["state_digests"] == {"a": "x"}
        assert store.entry(0)["status"] == "published"
        # no staging leftovers after a clean publish
        assert not [n for n in os.listdir(tmp_path) if n.startswith(".stage")]

    def test_empty_generation_rejected(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        with pytest.raises(ValueError, match="no files"):
            store.publish(lambda d: None, step=0)
        assert store.published() == []

    def test_corrupt_latest_quarantined_and_prior_served(self, tmp_path):
        """The headline invariant: a corrupted newest generation is never
        selected — it moves to quarantine/ with a ledger reason and the
        walk falls back to the prior generation."""
        store = CheckpointStore(str(tmp_path))
        store.publish(write_files({"m.bin": b"one" * 100}), step=1)
        store.publish(write_files({"m.bin": b"two" * 100}), step=2)
        corrupt_generation(store, 1)
        latest = store.latest_valid()
        assert latest.number == 0 and latest.step == 1
        assert store.quarantined() == [1]
        entry = store.entry(1)
        assert entry["status"] == "quarantined"
        assert "digest" in entry["reason"]
        # the quarantined generation is out of the selectable set for good
        assert store.published() == [0]

    def test_two_corrupt_generations_fall_through(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=3)
        for step in (1, 2, 3):
            store.publish(write_files({"m.bin": bytes(100) + bytes([step])}),
                          step=step)
        corrupt_generation(store, 1)
        corrupt_generation(store, 2)
        latest = store.latest_valid()
        assert latest.number == 0
        assert store.quarantined() == [1, 2]

    def test_truncation_detected(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.publish(write_files({"m.bin": b"x" * 1000}), step=1)
        path = os.path.join(store.generations_dir, "gen-00000000", "m.bin")
        with open(path, "r+b") as fh:
            fh.truncate(500)
        assert "truncated" in store.verify(0)
        assert store.latest_valid() is None
        assert store.entry(0)["status"] == "quarantined"

    def test_missing_member_detected(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.publish(write_files({"m.bin": b"x"}), step=1)
        os.unlink(os.path.join(store.generations_dir, "gen-00000000",
                               "m.bin"))
        assert "unreadable" in store.verify(0)

    def test_load_raises_on_corruption(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.publish(write_files({"m.bin": b"y" * 64}), step=1)
        corrupt_generation(store, 0)
        with pytest.raises(ValueError, match="verification"):
            store.load(0)

    def test_gc_keep_last(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=2)
        for step in range(5):
            store.publish(write_files({"m.bin": bytes([step]) * 10}),
                          step=step)
        assert store.published() == [3, 4]
        assert store.entry(0)["status"] == "gc"
        # the ledger remembers everything ever published
        assert sorted(int(k) for k in store.ledger()["entries"]) == list(
            range(5))

    def test_gc_keep_every(self, tmp_path):
        # keep-every-N pins archival generations that outlive keep-last
        store = CheckpointStore(str(tmp_path), keep_last=2, keep_every=3)
        for step in range(8):
            store.publish(write_files({"m.bin": bytes([step]) * 10}),
                          step=step)
        # 0, 3, 6 survive via keep_every; 6, 7 via keep_last
        assert store.published() == [0, 3, 6, 7]

    def test_numbering_monotonic_after_gc_and_quarantine(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep_last=1)
        for step in range(3):
            store.publish(write_files({"m.bin": bytes([step])}), step=step)
        assert store.published() == [2]
        corrupt_generation(store, 2)
        assert store.latest_valid() is None  # 2 quarantined, 0/1 gc'd
        g = store.publish(write_files({"m.bin": b"new"}), step=9)
        assert g.number == 3  # never reuses a number

    def test_stale_staging_swept_on_construction(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        crash_dir = os.path.join(str(tmp_path), ".stage-gen-00000000-999")
        os.makedirs(crash_dir)
        with open(os.path.join(crash_dir, "half.bin"), "wb") as fh:
            fh.write(b"partial")
        CheckpointStore(str(tmp_path))  # reopening sweeps
        assert not os.path.exists(crash_dir)

    def test_torn_ledger_recovers_from_dir_scan(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        store.publish(write_files({"m.bin": b"ok"}), step=1)
        with open(store.ledger_path, "w") as fh:
            fh.write("{not json")
        reopened = CheckpointStore(str(tmp_path))
        assert reopened.latest_valid().number == 0
        assert reopened.next_number() == 1

    def test_failed_writer_leaves_no_trace(self, tmp_path):
        store = CheckpointStore(str(tmp_path))

        def bad(directory):
            with open(os.path.join(directory, "a.bin"), "wb") as fh:
                fh.write(b"x")
            raise OSError("disk full")

        with pytest.raises(OSError):
            store.publish(bad, step=1)
        assert store.published() == []
        assert not [n for n in os.listdir(tmp_path) if n.startswith(".stage")]

    def test_retention_validation(self, tmp_path):
        with pytest.raises(ValueError):
            CheckpointStore(str(tmp_path), keep_last=0)
        with pytest.raises(ValueError):
            CheckpointStore(str(tmp_path), keep_every=-1)


# ===========================================================================
# faults
# ===========================================================================

class TestFaults:
    def test_seeded_schedule_is_deterministic(self):
        a = FaultSchedule.seeded(42, 100, kinds=("raise", "kill"), n_faults=3)
        b = FaultSchedule.seeded(42, 100, kinds=("raise", "kill"), n_faults=3)
        assert a == b
        assert len(a.specs) == 3
        assert all(1 <= s.step < 100 for s in a.specs)
        c = FaultSchedule.seeded(43, 100, kinds=("raise", "kill"), n_faults=3)
        assert a != c

    def test_schedule_json_round_trip(self, tmp_path):
        sched = FaultSchedule([
            FaultSpec(kind="kill", step=5),
            FaultSpec(kind="slow_write", step=2, args={"seconds": 0.5}),
        ])
        path = os.path.join(tmp_path, "f.json")
        sched.to_json(path)
        assert FaultSchedule.from_json(path) == sched

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSpec(kind="meteor", step=1)

    def test_raise_fires_once_at_exact_step(self):
        inj = FaultInjector(FaultSchedule([FaultSpec(kind="raise", step=3)]))
        inj.on_step(2)
        with pytest.raises(InjectedFault):
            inj.on_step(3)
        inj.on_step(3)  # already fired — never twice
        assert [e["kind"] for e in inj.log] == ["raise"]

    def test_slow_and_failed_write(self, tmp_path):
        sleeps = []
        inj = FaultInjector(
            FaultSchedule([
                FaultSpec(kind="slow_write", step=2, args={"seconds": 1.5}),
                FaultSpec(kind="fail_write", step=4),
            ]),
            sleep=sleeps.append,
        )
        store = CheckpointStore(str(tmp_path), fault_injector=inj)
        store.publish(write_files({"m.bin": b"a"}), step=0)  # before both
        store.publish(write_files({"m.bin": b"b"}), step=3)  # slow fires
        assert sleeps == [1.5]
        with pytest.raises(OSError, match="injected"):
            store.publish(write_files({"m.bin": b"c"}), step=5)
        # the failed publish left no half-generation behind
        assert store.published() == [0, 1]
        assert store.latest_valid().number == 1

    def test_corrupt_on_published(self, tmp_path):
        inj = FaultInjector(
            FaultSchedule([FaultSpec(kind="corrupt", step=1)]))
        store = CheckpointStore(str(tmp_path))
        g = store.publish(write_files({"m.bin": b"q" * 64}), step=2)
        inj.on_published(store, g)
        assert store.verify(g.number) is not None
        assert inj.log[-1]["member"] == "m.bin"


# ===========================================================================
# supervisor — fast paths with a fake experiment (no jax)
# ===========================================================================

class FakeExperiment:
    """Counts steps; never touches jax. save/load shuttle the counter
    through a text file so restore semantics are exercised for real."""

    instances = []

    def __init__(self, config):
        self.config = config
        self.batch_counter = 0
        self.trained = []
        FakeExperiment.instances.append(self)
        self.dis_state = self.gan_state = None
        self.cv_state = None
        self.gen_params = None

    def train_iteration(self, feats, labels):
        self.trained.append(self.batch_counter)

    def save_models(self, directory=None):
        with open(os.path.join(directory, "state.txt"), "w") as fh:
            fh.write(str(self.batch_counter))

    def save_model_shard(self, directory, shard_index, shard_count):
        # the fake's "state" is one counter, replicated per shard — enough
        # to exercise the coordinated-publish/elastic-restore plumbing
        name = f"state_shard-{shard_index:04d}-of-{shard_count:04d}.txt"
        with open(os.path.join(directory, name), "w") as fh:
            fh.write(str(self.batch_counter))
        return [name]

    def load_models(self, directory=None):
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("state") and n.endswith(".txt"))
        with open(os.path.join(directory, names[0])) as fh:
            self.batch_counter = int(fh.read())
        return self.batch_counter


@pytest.fixture(autouse=True)
def _reset_fakes():
    FakeExperiment.instances = []
    yield


def fake_supervisor(tmp_path, sup_cfg, faults=None, sleeps=None):
    import dataclasses

    @dataclasses.dataclass
    class Cfg:
        batch_size_train: int = 4

    feats = np.zeros((16, 3), np.float32)
    labels = np.zeros((16, 2), np.float32)
    sup = TrainingSupervisor(
        Cfg(), sup_cfg, feats, labels,
        store_root=os.path.join(str(tmp_path), "store"),
        faults=faults,
        sleep=(sleeps.append if sleeps is not None else (lambda s: None)),
        experiment_factory=FakeExperiment,
    )
    # the fake has no states to digest — bypass the digest hook
    sup.state_digests = lambda exp: {"fake": str(exp.batch_counter)}
    return sup


class TestSupervisorFast:
    def test_segments_and_publish_cadence(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=10, publish_every=4))
        out = sup.run()
        assert out["status"] == "completed" and out["steps"] == 10
        # boundaries 4, 8 plus the off-cadence final state at 10
        assert [e["step"] for e in sup.events
                if e["event"] == "publish"] == [4, 8, 10]
        assert sup.store.latest_valid().step == 10

    def test_fault_retry_restores_from_newest_valid(self, tmp_path):
        inj = FaultInjector(FaultSchedule([FaultSpec(kind="raise", step=6)]))
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=10, publish_every=4),
            faults=inj)
        out = sup.run()
        assert out["status"] == "completed" and out["attempts_used"] == 1
        restores = [e for e in sup.events if e["event"] == "restore"]
        assert [r["step"] for r in restores] == [4]
        # attempt 2 replayed steps 4 and 5 (lost to the fault at 6)
        second = FakeExperiment.instances[-1]
        assert second.trained[:3] == [4, 5, 6]

    def test_retry_budget_exhaustion_is_terminal(self, tmp_path):
        # a fault that keeps firing: every attempt dies at its first step
        inj = FaultInjector(FaultSchedule(
            [FaultSpec(kind="raise", step=0) for _ in range(10)]))
        sleeps = []
        sup = fake_supervisor(
            tmp_path,
            SupervisorConfig(total_steps=5, publish_every=2, max_retries=3,
                             backoff_base_s=0.5, backoff_max_s=1.5),
            faults=inj, sleeps=sleeps)
        with pytest.raises(RetryBudgetExceeded, match="injected"):
            sup.run()
        # bounded exponential backoff: 0.5, 1.0, then capped at 1.5
        assert sleeps == [0.5, 1.0, 1.5]
        assert len([e for e in sup.events if e["event"] == "fault"]) == 4

    def test_preemption_checkpoints_then_exits_cleanly(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=100, publish_every=50))

        class PreemptAt:
            def __init__(self, step):
                self.step = step

            def on_step(self, step):
                if step == self.step:
                    sup.request_preemption()

            def on_published(self, store, generation):
                pass

        sup.faults = PreemptAt(7)
        out = sup.run()
        # the preemption flag is honored at the NEXT boundary: step 7 still
        # trains, then the supervisor publishes and exits
        assert out["status"] == "preempted"
        assert out["steps"] == 8
        assert sup.store.latest_valid().step == 8

    def test_sigterm_preemption_via_real_signal(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=100, publish_every=50))
        inj = FaultInjector(FaultSchedule(
            [FaultSpec(kind="preempt", step=5)]))
        sup.faults = inj
        old = signal.getsignal(signal.SIGTERM)
        try:
            sup.install_signal_handlers()
            out = sup.run()
        finally:
            signal.signal(signal.SIGTERM, old)
        assert out["status"] == "preempted"
        assert out["steps"] == 6
        assert sup.store.latest_valid().step == 6

    def test_phased_experiment_rejected_terminally(self, tmp_path):
        """The bit-exact contract requires the fused (step-keyed RNG)
        path: an experiment on the phased param-averaging path (host-side
        sequential RNG draws) is rejected with a terminal error — never
        retried into the same wall."""
        sleeps = []
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=5, max_retries=3),
            sleeps=sleeps)

        def phased_factory(cfg):
            exp = FakeExperiment(cfg)
            exp._fused = None  # the phased-path marker
            return exp

        sup._experiment_factory = phased_factory
        with pytest.raises(UnsupportedExperimentError, match="phased"):
            sup.run()
        assert sleeps == []  # terminal: no backoff, no retries

    def test_preempt_flag_resets_between_runs(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=6, publish_every=3))
        sup.request_preemption()
        out = sup.run()
        # the stale flag from before run() must not poison the fresh run
        assert out["status"] == "completed" and out["steps"] == 6

    def test_resume_skips_when_nothing_remains(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=6, publish_every=3))
        out = sup.run()
        assert out["steps"] == 6
        sup2 = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=6, publish_every=3))
        out2 = sup2.run()
        assert out2["status"] == "completed" and out2["start_step"] == 6
        assert out2["final_generation"] == out["final_generation"]

    def test_batch_schedule_is_pure_function_of_step(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=4, publish_every=4))
        f0, _ = sup.batch_at(0)
        f4, _ = sup.batch_at(4)  # 16 rows / 4 per batch → wraps at 4
        np.testing.assert_array_equal(f0, f4)
        f1, _ = sup.batch_at(1)
        assert f1.shape == f0.shape
        sup2 = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=4, publish_every=4))
        np.testing.assert_array_equal(f0, sup2.batch_at(0)[0])

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SupervisorConfig(total_steps=0).validate()
        with pytest.raises(ValueError):
            SupervisorConfig(total_steps=1, publish_every=0).validate()
        with pytest.raises(ValueError):
            SupervisorConfig(total_steps=1, backoff_base_s=2.0,
                             backoff_max_s=1.0).validate()


# ===========================================================================
# supervisor — real GanExperiment (tabular tiny): the bit-exact contract
# ===========================================================================

def tabular_cfg(tmp_path):
    from gan_deeplearning4j_tpu.harness import ExperimentConfig

    return ExperimentConfig(
        model_family="tabular", num_features=16, z_size=4,
        batch_size_train=8, batch_size_pred=8, height=1, width=1, channels=1,
        save_models=False, output_dir=os.path.join(str(tmp_path), "out"),
    )


def tabular_data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.random((n, 16), dtype=np.float32)
    labels = np.eye(10, dtype=np.float32)[np.arange(n) % 10]
    return feats, labels


class TestSupervisorBitExact:
    def test_interrupted_resume_is_bit_exact(self, tmp_path):
        """The drill's core invariant, in-process: a run killed (trappable
        fault) at step 6 and resumed from the step-4 generation finishes
        with state digests IDENTICAL to an uninterrupted run of equal
        total steps."""
        feats, labels = tabular_data()
        cfg = tabular_cfg(tmp_path)
        oracle = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=8, publish_every=4),
            feats, labels,
            store_root=os.path.join(str(tmp_path), "s_oracle"))
        r1 = oracle.run()
        assert r1["status"] == "completed"

        inj = FaultInjector(FaultSchedule([FaultSpec(kind="raise", step=6)]))
        faulted = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=8, publish_every=4,
                                  backoff_base_s=0.0),
            feats, labels,
            store_root=os.path.join(str(tmp_path), "s_fault"),
            faults=inj, sleep=lambda s: None)
        r2 = faulted.run()
        assert r2["status"] == "completed" and r2["attempts_used"] == 1
        assert r1["state_digests"] == r2["state_digests"]
        # and the digests cover every trained state
        assert set(r1["state_digests"]) == {"dis", "gan", "gen"}

    def test_corrupt_generation_falls_back_and_still_completes(self, tmp_path):
        feats, labels = tabular_data()
        cfg = tabular_cfg(tmp_path)
        root = os.path.join(str(tmp_path), "s")
        first = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=6, publish_every=3),
            feats, labels, store_root=root)
        first.run()
        store = CheckpointStore(root)
        newest = store.published()[-1]
        corrupt_generation(store, newest)
        resumed = TrainingSupervisor(
            cfg, SupervisorConfig(total_steps=9, publish_every=3),
            feats, labels, store=CheckpointStore(root))
        out = resumed.run()
        assert out["status"] == "completed" and out["steps"] == 9
        restores = [e for e in resumed.events if e["event"] == "restore"]
        assert restores and restores[0]["generation"] != newest
        assert CheckpointStore(root).entry(newest)["status"] == "quarantined"


# ===========================================================================
# publish_for_serving into a store generation (versioned serving source)
# ===========================================================================

class TestServingGeneration:
    def test_bundle_publishes_as_generation(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        exp = GanExperiment(tabular_cfg(tmp_path))
        store = CheckpointStore(os.path.join(str(tmp_path), "store"))
        out = exp.publish_for_serving(store=store)
        assert out["generation"] == 0
        gen = store.latest_valid()
        assert gen is not None and gen.manifest["kind"] == "serving"
        with open(gen.file("serving.json")) as fh:
            manifest = json.load(fh)
        assert manifest["generation"] == 0
        assert manifest["generator"] in gen.manifest["files"]
        # a second publish gets the next number
        out2 = exp.publish_for_serving(store=store)
        assert out2["generation"] == 1

    def test_directory_publish_is_unversioned(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        exp = GanExperiment(tabular_cfg(tmp_path))
        out = exp.publish_for_serving(
            directory=os.path.join(str(tmp_path), "serving"))
        assert out["generation"] is None
        with open(os.path.join(out["directory"], "serving.json")) as fh:
            assert json.load(fh)["generation"] is None


# ===========================================================================
# the drill smoke — a real kill at step N, tier-1 on CPU
# ===========================================================================

class TestDrillSmoke:
    def test_drill_smoke_with_injected_kill(self, tmp_path):
        """End to end through real processes: SIGKILL at the scheduled
        step, relaunch, bit-exact recovery, corruption quarantine — the
        drill's own invariants gate its exit code."""
        out_json = os.path.join(str(tmp_path), "drill.json")
        proc = subprocess.run(
            [sys.executable, "scripts/resilience_drill.py", "--smoke",
             "--workdir", os.path.join(str(tmp_path), "work"),
             "--output", out_json],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=560,
        )
        assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-2000:])
        with open(out_json) as fh:
            payload = json.load(fh)
        assert payload["ok"] is True
        inv = payload["invariants"]
        assert inv["kill_observed"] and inv["bit_exact_resume"]
        assert inv["corrupt_never_selected"] and inv["recovered_within_budget"]
        results = payload["results"]
        assert results["kill_recover"]["completed"]
        assert results["oracle"]["publish_count"] >= 3
        assert results["oracle"]["checkpoint_overhead_frac"] < 1.0


# ===========================================================================
# the mesh plane — coordinated sharded checkpointing (resilience/mesh.py)
# ===========================================================================

def shard_writer(payload):
    """A mesh shard writer: writes a dict of name -> bytes, returns the
    names (the per-shard manifest's file list)."""
    def writer(directory):
        for name, data in payload.items():
            with open(os.path.join(directory, name), "wb") as fh:
                fh.write(data)
        return list(payload)
    return writer


def run_mesh(root, world_size, publish_args, token="t1", timeout_s=10.0,
             faults_by_worker=None, store=None):
    """Run one coordinated publish across ``world_size`` worker threads.
    Returns per-worker results: a Generation or the raised exception."""
    from concurrent.futures import ThreadPoolExecutor

    store = store or CheckpointStore(root)
    coords = [
        MeshCoordinator(
            root, worker=k, world_size=world_size, token=token,
            timeout_s=timeout_s,
            faults=(faults_by_worker or {}).get(k),
        )
        for k in range(world_size)
    ]

    def one(k):
        writer, step = publish_args(k)
        try:
            return coords[k].publish(store, writer, step=step)
        except Exception as exc:  # collected, asserted by the caller
            return exc

    with ThreadPoolExecutor(world_size) as pool:
        return list(pool.map(one, range(world_size))), store


class HookRaise:
    """A fault injector that raises at ONE named mesh hook — the
    in-process stand-in for a worker dying at that exact protocol point
    (the drill does it with real SIGKILLs)."""

    def __init__(self, hook):
        self.hook = hook

    def _fire(self, name):
        if name == self.hook:
            raise RuntimeError(f"injected death at {name}")

    def on_shard_write(self, step):
        self._fire("on_shard_write")

    def on_shard_staged(self, step):
        self._fire("on_shard_staged")

    def on_mesh_commit(self, step):
        self._fire("on_mesh_commit")

    def on_mesh_committed(self, step):
        self._fire("on_mesh_committed")


class TestMeshBarrier:
    def test_barrier_meets_across_workers(self, tmp_path):
        from concurrent.futures import ThreadPoolExecutor

        root = str(tmp_path)
        coords = [MeshCoordinator(root, worker=k, world_size=3,
                                  timeout_s=10.0) for k in range(3)]
        with ThreadPoolExecutor(3) as pool:
            list(pool.map(lambda c: c.barrier("up"), coords))  # no raise

    def test_barrier_timeout_is_loud(self, tmp_path):
        coord = MeshCoordinator(str(tmp_path), worker=0, world_size=2,
                                timeout_s=0.2)
        with pytest.raises(MeshTimeout, match="gang abort"):
            coord.barrier("up")

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError):
            MeshCoordinator(str(tmp_path), worker=2, world_size=2)
        with pytest.raises(ValueError):
            MeshCoordinator(str(tmp_path), worker=0, world_size=0)
        with pytest.raises(ValueError):
            MeshCoordinator(str(tmp_path), worker=0, world_size=1,
                            token="no/slashes")


class TestMeshPublish:
    def test_two_phase_publish_round_trip(self, tmp_path):
        root = os.path.join(str(tmp_path), "store")
        results, store = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"shard{k}.bin": bytes([k]) * 64}), 5))
        assert all(not isinstance(r, Exception) for r in results), results
        assert [g.number for g in results] == [0, 0]
        gen = store.latest_valid()
        assert gen is not None and gen.step == 5
        # the combined manifest covers every staged file and verifies
        assert {"shard0.bin", "shard1.bin"} <= set(gen.manifest["files"])
        assert store.verify(0) is None
        mesh = gen.manifest["mesh"]
        assert mesh["world_size"] == 2
        assert mesh["shards"] == ["SHARD-00000.json", "SHARD-00001.json"]
        # the whole-mesh digest is recomputable from the manifest alone
        assert mesh["mesh_digest"] == mesh_digest(gen.manifest["files"])
        assert store.entry(0)["status"] == "published"

    def test_second_round_gets_next_number(self, tmp_path):
        root = os.path.join(str(tmp_path), "store")
        _, store = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"a{k}.bin": b"x" * 8}), 3))
        results, _ = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"b{k}.bin": b"y" * 8}), 6),
            store=store)
        assert [g.number for g in results] == [1, 1]
        assert store.published() == [0, 1]

    def test_empty_shard_rejected(self, tmp_path):
        root = os.path.join(str(tmp_path), "store")
        results, _ = run_mesh(
            root, 1, lambda k: (shard_writer({}), 1), timeout_s=1.0)
        assert isinstance(results[0], Exception)
        assert "empty shard" in str(results[0])

    def test_colliding_shard_files_rejected(self, tmp_path):
        from gan_deeplearning4j_tpu.resilience import MeshProtocolError

        root = os.path.join(str(tmp_path), "store")
        results, store = run_mesh(
            root, 2,
            lambda k: (shard_writer({"same.bin": bytes([k]) * 8}), 1),
            timeout_s=2.0)
        # the coordinator refuses the commit; nothing publishes
        assert any(isinstance(r, MeshProtocolError) for r in results)
        assert store.latest_valid() is None


class TestMeshCommitWindow:
    """The satellite invariant: a writer killed anywhere inside the commit
    window leaves a round ``latest_valid()`` can NEVER surface — it falls
    back to the previous generation, and the corpse is swept on the next
    gang's open."""

    def _prior_generation(self, root):
        store = CheckpointStore(root)
        store.publish(write_files({"prior.bin": b"prior"}), step=1)
        return store

    def _stage_dirs(self, root):
        from gan_deeplearning4j_tpu.resilience.mesh import MESH_STAGE_PREFIX

        return sorted(d for d in os.listdir(root)
                      if d.startswith(MESH_STAGE_PREFIX))

    @pytest.mark.parametrize("hook,marker_expected", [
        # killed between shard staging and the mesh commit: no marker
        ("on_mesh_commit", False),
        # killed between the commit marker and the rename/ledger write:
        # the marker exists — but only inside the staging dir
        ("on_mesh_committed", True),
    ])
    def test_coordinator_killed_in_commit_window(self, tmp_path, hook,
                                                 marker_expected):
        root = os.path.join(str(tmp_path), "store")
        store = self._prior_generation(root)
        results, _ = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"s{k}.bin": b"z" * 16}), 4),
            timeout_s=1.5, store=store,
            faults_by_worker={0: HookRaise(hook)})
        assert isinstance(results[0], RuntimeError)  # the injected death
        assert isinstance(results[1], MeshTimeout)   # peer gang-aborts
        # the round is a corpse in staging: latest_valid falls back to the
        # prior generation and the ledger never saw the attempt
        leftovers = self._stage_dirs(root)
        assert len(leftovers) == 1
        marker = os.path.join(root, leftovers[0], "MANIFEST.json")
        assert os.path.exists(marker) == marker_expected
        latest = store.latest_valid()
        assert latest is not None and latest.number == 0 and latest.step == 1
        assert store.entry(1) == {}
        # the next gang's coordinator (fresh token) sweeps the corpse
        MeshCoordinator(root, worker=0, world_size=2, token="t2")
        assert self._stage_dirs(root) == []
        # and can publish the SAME number cleanly afterwards
        results, _ = run_mesh(root, 2,
                              lambda k: (shard_writer({f"n{k}.bin": b"n"}),
                                         4),
                              token="t2", store=store)
        assert [g.number for g in results] == [1, 1]
        assert store.latest_valid().number == 1

    def test_worker_killed_before_vote_aborts_commit(self, tmp_path):
        root = os.path.join(str(tmp_path), "store")
        store = self._prior_generation(root)
        results, _ = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"s{k}.bin": b"z" * 16}), 4),
            timeout_s=1.5, store=store,
            faults_by_worker={1: HookRaise("on_shard_write")})
        assert isinstance(results[1], RuntimeError)
        assert isinstance(results[0], MeshTimeout)  # coordinator aborts
        latest = store.latest_valid()
        assert latest is not None and latest.number == 0

    def test_straggler_shard_writer_still_commits(self, tmp_path):
        import time

        class SleepAt:
            def on_shard_write(self, step):
                time.sleep(0.3)

            def on_shard_staged(self, step):
                pass

        root = os.path.join(str(tmp_path), "store")
        results, store = run_mesh(
            root, 2,
            lambda k: (shard_writer({f"s{k}.bin": b"z" * 16}), 4),
            timeout_s=5.0, faults_by_worker={1: SleepAt()})
        assert all(not isinstance(r, Exception) for r in results), results
        assert store.latest_valid().number == 0


class TestMeshSupervisor:
    """The supervisor's mesh mode, on fakes: coordinated publishes at the
    shared cadence, one restore decision for the gang, gang abort on a
    dead peer."""

    def _run_gang(self, tmp_path, total, token, world=2, dead=()):
        from concurrent.futures import ThreadPoolExecutor

        root = os.path.join(str(tmp_path), "store")

        def one(k):
            if k in dead:
                return None  # never launched — peers must gang-abort
            coord = MeshCoordinator(root, worker=k, world_size=world,
                                    token=token, timeout_s=1.5,
                                    boot_timeout_s=1.5)
            sup = fake_supervisor(
                tmp_path, SupervisorConfig(total_steps=total,
                                           publish_every=4))
            sup.store = CheckpointStore(root)
            sup.mesh = coord
            try:
                return sup.run()
            except MeshTimeout as exc:
                return exc

        with ThreadPoolExecutor(world) as pool:
            return list(pool.map(one, range(world)))

    def test_coordinated_cadence_and_elastic_restore(self, tmp_path):
        out = self._run_gang(tmp_path, total=10, token="tA")
        assert all(o["status"] == "completed" for o in out)
        store = CheckpointStore(os.path.join(str(tmp_path), "store"))
        gen = store.latest_valid()
        assert gen.step == 10 and gen.manifest["mesh"]["world_size"] == 2
        # both shard files of the final round are in the manifest
        shard_files = [n for n in gen.manifest["files"]
                       if "state_shard" in n]
        assert len(shard_files) == 2
        # a second gang (fresh token) restores from the mesh generation
        # and both workers agree on the restored counter
        out2 = self._run_gang(tmp_path, total=16, token="tB")
        assert all(o["status"] == "completed" for o in out2)
        for o in out2:
            restores = [e for e in o["events"] if e["event"] == "restore"]
            assert [r["step"] for r in restores] == [10]
        assert store.latest_valid().step == 16

    def test_dead_peer_gang_aborts_both_phases(self, tmp_path):
        out = self._run_gang(tmp_path, total=10, token="tC", dead=(1,))
        assert out[1] is None
        assert isinstance(out[0], MeshTimeout)
        # nothing half-published
        store = CheckpointStore(os.path.join(str(tmp_path), "store"))
        assert store.latest_valid() is None


class TestMeshReshardParity:
    def test_generation_written_by_m_workers_restores_bit_exact(
            self, tmp_path):
        """The elastic-resume contract, in-process: generations of the
        SAME trained state written by M∈{1,2,4} shard writers all restore
        digest-identical onto a fresh single experiment (N=1, the serve
        path) — resharding is a pure regrouping of bytes. The drill
        proves the N=2 process-level half."""
        from concurrent.futures import ThreadPoolExecutor

        from gan_deeplearning4j_tpu.harness import GanExperiment
        from gan_deeplearning4j_tpu.resilience.store import tree_digest

        feats, labels = tabular_data()
        cfg = tabular_cfg(tmp_path)
        exp = GanExperiment(cfg)
        for step in range(2):
            f, l = feats[:8], labels[:8]
            exp.train_iteration(f, l)
            exp.batch_counter += 1
        want = {
            "dis": tree_digest(exp.dis_state),
            "gan": tree_digest(exp.gan_state),
            "gen": tree_digest(exp.gen_params),
        }

        root = os.path.join(str(tmp_path), "store")
        store = CheckpointStore(root)
        by_m = {}
        for m in (1, 2, 4):
            coords = [MeshCoordinator(root, worker=k, world_size=m,
                                      token=f"m{m}", timeout_s=20.0)
                      for k in range(m)]

            def publish(k, m=m, coords=coords):
                return coords[k].publish(
                    store,
                    lambda d: exp.save_model_shard(d, k, m),
                    step=2)

            with ThreadPoolExecutor(m) as pool:
                gens = list(pool.map(publish, range(m)))
            by_m[m] = gens[0]

        for m, gen in by_m.items():
            fresh = GanExperiment(cfg)
            assert fresh.load_models(directory=gen.path) == 2
            got = {
                "dis": tree_digest(fresh.dis_state),
                "gan": tree_digest(fresh.gan_state),
                "gen": tree_digest(fresh.gen_params),
            }
            assert got == want, f"M={m} restore diverged"

    def test_partial_mesh_generation_refused(self, tmp_path):
        """A generation directory with a missing shard (however it got
        that way) must refuse to restore, never half-load."""
        from gan_deeplearning4j_tpu.harness import GanExperiment

        cfg = tabular_cfg(tmp_path)
        exp = GanExperiment(cfg)
        d = os.path.join(str(tmp_path), "gen")
        os.makedirs(d)
        exp.save_model_shard(d, 0, 2)  # shard 1 of 2 never lands
        fresh = GanExperiment(cfg)
        with pytest.raises(ValueError, match="incomplete"):
            fresh.load_models(directory=d)


# ===========================================================================
# bounded-retry reads — transient store I/O (shared-filesystem flakes)
# ===========================================================================

class TestReadRetries:
    def _flaky_hash(self, monkeypatch, failures, member="m.bin"):
        from gan_deeplearning4j_tpu.resilience import store as store_mod

        real = store_mod._hash_file
        budget = {"n": failures}

        def flaky(path, fsync=False):
            if budget["n"] > 0 and path.endswith(member):
                budget["n"] -= 1
                raise OSError("injected transient EIO")
            return real(path, fsync)

        monkeypatch.setattr(store_mod, "_hash_file", flaky)
        return budget

    def test_transient_read_retried_not_quarantined(self, tmp_path,
                                                    monkeypatch):
        sleeps = []
        store = CheckpointStore(os.path.join(str(tmp_path), "s"),
                                read_retries=2, sleep=sleeps.append)
        store.publish(write_files({"m.bin": b"good bytes"}), step=1)
        self._flaky_hash(monkeypatch, failures=2)
        gen = store.latest_valid()
        assert gen is not None and gen.number == 0
        assert store.quarantined() == []  # the flake did NOT condemn it
        # capped exponential backoff between attempts
        assert sleeps == [0.05, 0.1]

    def test_retries_exhausted_falls_back(self, tmp_path, monkeypatch):
        store = CheckpointStore(os.path.join(str(tmp_path), "s"),
                                read_retries=1, sleep=lambda s: None)
        store.publish(write_files({"old.bin": b"old"}), step=1)
        store.publish(write_files({"m.bin": b"new"}), step=2)
        self._flaky_hash(monkeypatch, failures=50)  # a hard failure
        gen = store.latest_valid()
        # the persistently-unreadable newest generation quarantines and
        # the walk falls back — exactly the old behavior, two reads later
        assert gen is not None and gen.number == 0
        assert store.quarantined() == [1]
        assert "unreadable" in store.entry(1)["reason"]

    def test_zero_retries_fails_fast(self, tmp_path, monkeypatch):
        sleeps = []
        store = CheckpointStore(os.path.join(str(tmp_path), "s"),
                                read_retries=0, sleep=sleeps.append)
        store.publish(write_files({"m.bin": b"x"}), step=1)
        self._flaky_hash(monkeypatch, failures=1)
        assert store.verify(0) is not None  # first error is the verdict
        assert sleeps == []

    def test_retry_counter_in_registry(self, tmp_path, monkeypatch):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        def retried_total():
            fam = get_registry().snapshot().get(
                "resilience_read_retries_total", {})
            return sum(s["value"] for s in fam.get("series", []))

        store = CheckpointStore(os.path.join(str(tmp_path), "s"),
                                read_retries=2, sleep=lambda s: None)
        store.publish(write_files({"m.bin": b"x"}), step=1)
        before = retried_total()
        self._flaky_hash(monkeypatch, failures=2)
        assert store.verify(0) is None
        assert retried_total() - before == 2


# ===========================================================================
# the multihost drill — real processes, coordinated store, slow-gated
# ===========================================================================

class TestMultihostDrill:
    @pytest.mark.slow
    def test_multihost_drill_smoke(self, tmp_path):
        """End to end through real worker gangs: straggler + worker
        SIGKILL (survivor gang-aborts with 76), coordinator killed inside
        the commit window (the half-committed round never surfaces),
        bit-exact recovery, and elastic 2→{1,2} resume — the drill's own
        invariants gate its exit code."""
        out_json = os.path.join(str(tmp_path), "drill_mh.json")
        proc = subprocess.run(
            [sys.executable, "scripts/resilience_drill.py", "--smoke",
             "--multihost", "2",
             "--workdir", os.path.join(str(tmp_path), "work"),
             "--output", out_json],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=1100,
        )
        assert proc.returncode == 0, (proc.stdout[-3000:],
                                      proc.stderr[-2000:])
        with open(out_json) as fh:
            payload = json.load(fh)
        assert payload["ok"] is True
        inv = payload["invariants"]
        assert inv["mh_kill_observed"] and inv["mh_gang_aborted"]
        assert inv["mh_no_partial_generation"]
        assert inv["mh_bit_exact_resume"] and inv["mh_workers_agree"]
        assert inv["mh_commit_window_all_or_nothing"]
        assert inv["mh_commit_window_recovered"]
        assert inv["mh_elastic_mesh_to_single"]
        assert inv["mh_elastic_mesh_to_mesh"]
        results = payload["results"]
        assert results["kill_recover"]["lost_steps"] >= 0
        assert results["commit_window"]["stage_leftovers"]


# ===========================================================================
# step timelines + mesh publish phase attribution (ISSUE-11)
# ===========================================================================

class TestStepTimeline:
    def test_summary_carries_per_phase_timeline(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=6, publish_every=3))
        out = sup.run()
        timeline = out["step_timeline"]
        steps = [e for e in timeline if e["phase"] == "step"]
        publishes = [e for e in timeline if e["phase"] == "publish"]
        assert [e["step"] for e in steps] == list(range(6))
        # boundaries 3 and 6 (the final publish is ON the boundary here)
        assert [e["step"] for e in publishes] == [3, 6]
        for e in timeline:
            assert e["start_unix_s"] > 0 and e["seconds"] >= 0
        assert [e["generation"] for e in publishes] == [0, 1]
        # single-process run: no mesh identity in the summary
        assert out["worker"] is None and out["world_size"] is None

    def test_timeline_is_bounded(self, tmp_path):
        sup = fake_supervisor(
            tmp_path, SupervisorConfig(total_steps=2, publish_every=2))
        assert sup._timeline.maxlen == 4096

    def test_mesh_publish_stamps_phases(self, tmp_path):
        root = os.path.join(str(tmp_path), "store")
        store = CheckpointStore(root)
        coords = [
            MeshCoordinator(root, worker=k, world_size=2, token="tl",
                            timeout_s=10.0)
            for k in range(2)
        ]
        from concurrent.futures import ThreadPoolExecutor

        def one(k):
            return coords[k].publish(
                store, shard_writer({f"s{k}.bin": bytes([k]) * 32}), step=1)

        with ThreadPoolExecutor(2) as pool:
            list(pool.map(one, range(2)))
        for coord in coords:
            phases = coord.last_phases
            assert set(phases) == {"announce_s", "stage_s", "commit_wait_s"}
            assert all(v >= 0 for v in phases.values())

    def test_mesh_phase_spans_feed_the_barrier_table(self, tmp_path):
        from gan_deeplearning4j_tpu.telemetry.trace import TRACER

        TRACER.enable()
        root = os.path.join(str(tmp_path), "store")
        store = CheckpointStore(root)
        coords = [
            MeshCoordinator(root, worker=k, world_size=2, token="tb",
                            timeout_s=10.0)
            for k in range(2)
        ]
        from concurrent.futures import ThreadPoolExecutor

        def one(k):
            if k == 1:
                time.sleep(0.05)  # worker 1 is the deliberate straggler
            return coords[k].publish(
                store, shard_writer({f"s{k}.bin": bytes([k]) * 32}), step=1)

        with ThreadPoolExecutor(2) as pool:
            list(pool.map(one, range(2)))
        events = TRACER.events()
        stage = [e for e in events if e["name"] == "resilience.mesh_stage"]
        wait = [e for e in events
                if e["name"] == "resilience.mesh_commit_wait"]
        assert {e["args"]["worker"] for e in stage} == {0, 1}
        assert {e["args"]["worker"] for e in wait} == {0, 1}
        # fold through trace_report's attribution: in-process both workers
        # share one pid, but the table keys on the worker ARG
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import trace_report
            spans = [
                {"name": e["name"], "ts": e["ts"], "dur": e.get("dur", 0.0),
                 "pid": e["pid"], "args": e.get("args") or {}}
                for e in events if e.get("ph") == "X"
            ]
            table = trace_report._barrier_table(spans)
        finally:
            sys.path.remove(os.path.join(REPO, "scripts"))
        [entry] = table
        assert set(entry["workers"]) == {"0", "1"}
        assert entry["straggler"] in (0, 1)
