"""Model-family tests: tabular MLP-GAN, CIFAR-10/CelebA image DCGANs, WGAN-GP.

Mirrors the reference's smoke-check style (shape assertions after init +
forward, SURVEY §4.1) plus training-moves-the-loss checks and weight-sync
round trips for each family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.models import dcgan_image, mlp_gan, wgan_gp
from gan_deeplearning4j_tpu.nn import ComputationGraph
from gan_deeplearning4j_tpu.parallel import GraphTrainer
from gan_deeplearning4j_tpu.runtime import TpuEnvironment


class TestMlpGan:
    @pytest.mark.slow
    def test_shapes(self):
        cfg = mlp_gan.MlpGanConfig(num_features=16, z_size=4, hidden=(32, 32))
        dis, gen, gan = (
            mlp_gan.build_discriminator(cfg),
            mlp_gan.build_generator(cfg),
            mlp_gan.build_gan(cfg),
        )
        x = jnp.ones((6, 16))
        z = jnp.ones((6, 4))
        assert dis.output(dis.init(), x).shape == (6, 1)
        assert gen.output(gen.init(), z).shape == (6, 16)
        assert gan.output(gan.init(), z).shape == (6, 1)

    def test_sync_maps_cover_all_param_layers(self):
        cfg = mlp_gan.MlpGanConfig(num_features=16, z_size=4, hidden=(32, 32))
        dis, gen, gan = (
            mlp_gan.build_discriminator(cfg),
            mlp_gan.build_generator(cfg),
            mlp_gan.build_gan(cfg),
        )
        dis_to_gan, gan_to_gen = mlp_gan.sync_maps(cfg)
        dis_params, gen_params, gan_params = dis.init(), gen.init(), gan.init()
        # every map entry resolves and copies without shape errors
        merged = ComputationGraph.copy_params(dis_params, gan_params, dis_to_gan)
        merged2 = ComputationGraph.copy_params(merged, gen_params, gan_to_gen)
        for src, dst in dis_to_gan.items():
            for p, v in dis_params[src].items():
                np.testing.assert_array_equal(np.asarray(merged[dst][p]), np.asarray(v))
        # gen got gan's generator-side weights
        for src, dst in gan_to_gen.items():
            for p in merged[src]:
                np.testing.assert_array_equal(
                    np.asarray(merged2[dst][p]), np.asarray(merged[src][p])
                )

    def test_training_reduces_loss(self):
        cfg = mlp_gan.MlpGanConfig(num_features=13, z_size=4, hidden=(32,))
        dis = mlp_gan.build_discriminator(cfg)
        trainer = GraphTrainer(dis)
        state = trainer.init_state()
        data = mlp_gan.synthetic_transactions(64, num_features=13, seed=1)
        labels = np.ones((64, 1), np.float32)  # teach D "this is real"
        first = last = None
        for _ in range(12):
            state, loss = trainer.train_step(state, jnp.asarray(data), jnp.asarray(labels))
            first = float(loss) if first is None else first
            last = float(loss)
        assert last < first

    def test_synthetic_transactions_contract(self):
        t = mlp_gan.synthetic_transactions(100, num_features=32, seed=2)
        assert t.shape == (100, 32) and t.dtype == np.float32
        assert t.min() >= 0.0 and t.max() <= 1.0
        # deterministic per seed
        np.testing.assert_array_equal(t, mlp_gan.synthetic_transactions(100, 32, seed=2))
        # structured: features correlate (not white noise)
        corr = np.corrcoef(t.T)
        off = np.abs(corr[np.triu_indices(32, k=1)])
        assert off.max() > 0.3


class TestImageDcgan:
    @pytest.mark.parametrize("cfg", [dcgan_image.CIFAR10, dcgan_image.CELEBA64])
    def test_shapes(self, cfg):
        small = dcgan_image.ImageGanConfig(
            height=cfg.height, width=cfg.width, channels=cfg.channels,
            z_size=8, base_filters=8, dense_width=32,
        )
        dis, gen, gan = (
            dcgan_image.build_discriminator(small),
            dcgan_image.build_generator(small),
            dcgan_image.build_gan(small),
        )
        n = 2
        x = jnp.ones((n, small.num_features))
        z = jnp.ones((n, small.z_size))
        assert dis.output(dis.init(), x).shape == (n, 1)
        img = gen.output(gen.init(), z)
        assert img.shape == (n, cfg.height, cfg.width, cfg.channels)
        assert gan.output(gan.init(), z).shape == (n, 1)

    def test_sync_maps_resolve(self):
        small = dcgan_image.ImageGanConfig(z_size=8, base_filters=8, dense_width=32)
        dis, gen, gan = (
            dcgan_image.build_discriminator(small),
            dcgan_image.build_generator(small),
            dcgan_image.build_gan(small),
        )
        dis_to_gan, gan_to_gen = dcgan_image.sync_maps(small)
        merged = ComputationGraph.copy_params(dis.init(), gan.init(), dis_to_gan)
        ComputationGraph.copy_params(merged, gen.init(), gan_to_gen)
        # maps cover every parameterized dis layer
        dis_param_layers = {n for n, p in dis.init().items() if p}
        assert dis_param_layers == set(dis_to_gan)

    def test_bad_side_raises(self):
        with pytest.raises(ValueError):
            dcgan_image.ImageGanConfig(height=28, width=28).stages

    def test_synthetic_images_contract(self):
        small = dcgan_image.ImageGanConfig(z_size=8, base_filters=8, dense_width=32)
        imgs = dcgan_image.synthetic_images(5, small, seed=3)
        assert imgs.shape == (5, small.num_features)
        assert imgs.min() >= 0.0 and imgs.max() <= 1.0


class TestWganGp:
    def _small(self):
        return wgan_gp.WganGpConfig(
            height=8, width=8, channels=1, z_size=4, base_filters=4,
            dense_width=16, n_critic=2,
        )

    @pytest.mark.slow
    def test_shapes_and_round(self):
        cfg = self._small()
        tr = wgan_gp.WganGpTrainer(cfg)
        critic_state, gen_state = tr.init_states(seed=0)
        b = 6
        real = np.random.default_rng(0).random(
            (cfg.n_critic, b, cfg.num_features), np.float32
        )
        critic_state, gen_state, c_loss, g_loss = tr.train_round(
            critic_state, gen_state, real, jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(c_loss)) and np.isfinite(float(g_loss))
        assert int(critic_state.step) == cfg.n_critic
        assert int(gen_state.step) == 1
        imgs = tr.sample(gen_state, jax.random.PRNGKey(2), 3)
        assert imgs.shape == (3, 8, 8, 1)
        assert float(jnp.min(imgs)) >= 0.0 and float(jnp.max(imgs)) <= 1.0

    def test_gradient_penalty_pulls_norm_to_one(self):
        # after several critic rounds on fixed data, the critic's input-grad
        # norm at interpolates should move toward 1 (the GP target)
        cfg = self._small()
        tr = wgan_gp.WganGpTrainer(cfg)
        critic_state, gen_state = tr.init_states(seed=0)
        rng = np.random.default_rng(1)
        real = rng.random((cfg.n_critic, 8, cfg.num_features), np.float32)

        def grad_norm(params):
            x = jnp.asarray(real[0])

            def s(x):
                return jnp.sum(tr.critic.output(params, x, train=False))

            g = jax.grad(s)(x)
            return float(jnp.mean(jnp.sqrt(jnp.sum(g**2, axis=1))))

        before = abs(grad_norm(critic_state.params) - 1.0)
        key = jax.random.PRNGKey(0)
        for i in range(10):
            key, sub = jax.random.split(key)
            critic_state, gen_state, _, _ = tr.train_round(
                critic_state, gen_state, real, sub
            )
        after = abs(grad_norm(critic_state.params) - 1.0)
        assert after < before

    def test_critic_round_count_validation(self):
        cfg = self._small()
        tr = wgan_gp.WganGpTrainer(cfg)
        cs, gs = tr.init_states()
        bad = np.zeros((cfg.n_critic + 1, 4, cfg.num_features), np.float32)
        with pytest.raises(ValueError):
            tr.train_round(cs, gs, bad, jax.random.PRNGKey(0))

    def test_data_parallel_round(self):
        cfg = self._small()
        mesh = TpuEnvironment().make_mesh()
        tr = wgan_gp.WganGpTrainer(cfg, mesh=mesh)
        critic_state, gen_state = tr.init_states(seed=0)
        b = 16  # divisible by the 8-device fake mesh
        real = np.random.default_rng(0).random(
            (cfg.n_critic, b, cfg.num_features), np.float32
        )
        critic_state, gen_state, c_loss, g_loss = tr.train_round(
            critic_state, gen_state, real, jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(c_loss)) and np.isfinite(float(g_loss))
