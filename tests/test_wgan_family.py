"""WGAN-GP as a registry family (round-1 VERDICT weak #4): CLI config,
checkpoint/resume, exports, and the experiment factory all treat BASELINE.md
config 5 as a first-class run."""

import os

import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import ArrayDataSetIterator
from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment
from gan_deeplearning4j_tpu.harness.wgan_experiment import WganGpExperiment
from gan_deeplearning4j_tpu.models import registry


def tiny_config(tmp_path, **overrides) -> ExperimentConfig:
    base = dict(
        model_family="wgan_gp",
        height=8, width=8, channels=1, num_features=64, z_size=4,
        batch_size_train=8, batch_size_pred=8, n_critic=2,
        num_iterations=1, latent_grid=2,
        data_dir=str(tmp_path / "data"), output_dir=str(tmp_path / "out"),
        save_models=False,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


class TestRegistryCitizenship:
    def test_family_registered(self):
        fam = registry.get("wgan_gp")
        assert fam.name == "wgan_gp" and fam.make_experiment is not None
        assert "wgan_gp" in registry.names()

    def test_factory_dispatch(self, tmp_path):
        exp = make_experiment(tiny_config(tmp_path))
        assert isinstance(exp, WganGpExperiment)
        assert exp.cv is None

    def test_config_validation(self):
        with pytest.raises(ValueError):  # 10 % 3 != 0
            ExperimentConfig(
                model_family="wgan_gp", batch_size_train=10, n_critic=3,
                height=8, width=8, channels=1, num_features=64,
            ).validate()
        with pytest.raises(ValueError):  # no param averaging for wgan
            ExperimentConfig(
                model_family="wgan_gp", distributed="param_averaging",
                height=8, width=8, channels=1, num_features=64,
                batch_size_train=10, n_critic=5,
            ).validate()

    def test_model_cfg_maps_knobs(self, tmp_path):
        exp = make_experiment(tiny_config(tmp_path, gp_lambda=5.0, n_critic=4,
                                          batch_size_train=8))
        assert exp.model_cfg.gp_lambda == 5.0 and exp.model_cfg.n_critic == 4


class TestWganExperimentLoop:
    def test_run_end_to_end(self, tmp_path):
        cfg = tiny_config(tmp_path, save_models=True)
        exp = make_experiment(cfg)
        fam = registry.get("wgan_gp")
        feats = fam.synthetic_data(16, exp.model_cfg, 0)
        labels = np.eye(10, dtype=np.float32)[np.arange(16) % 10]
        train = ArrayDataSetIterator(feats, labels, batch_size=8)
        result = exp.run(train)
        assert result["iterations"] == 1
        h = result["history"][0]
        assert np.isfinite(h["d_loss"]) and np.isfinite(h["g_loss"])
        assert np.isnan(h["cv_loss"])  # no transfer classifier
        manifold = np.loadtxt(
            os.path.join(cfg.output_dir, "mnist_out_1.csv"), delimiter=","
        )
        assert manifold.shape == (4, 64)
        assert manifold.min() >= 0.0 and manifold.max() <= 1.0  # sigmoid image
        for name in ("critic", "gen"):
            assert os.path.exists(
                os.path.join(cfg.output_dir, f"mnist_{name}_model.zip")
            )

    def test_checkpoint_resume_roundtrip(self, tmp_path):
        import jax

        cfg = tiny_config(tmp_path, save_models=True)
        exp = make_experiment(cfg)
        fam = registry.get("wgan_gp")
        feats = fam.synthetic_data(8, exp.model_cfg, 0)
        exp.train_iteration(feats)
        exp.save_models()

        exp2 = make_experiment(cfg)
        restored = exp2.load_models()
        assert restored == int(exp.gen_state.step)
        jax.tree_util.tree_map(
            lambda u, v: np.testing.assert_array_equal(np.asarray(u), np.asarray(v)),
            exp.critic_state.params, exp2.critic_state.params,
        )
        jax.tree_util.tree_map(
            lambda u, v: np.testing.assert_array_equal(np.asarray(u), np.asarray(v)),
            exp.critic_state.opt_state, exp2.critic_state.opt_state,
        )
        # resumed training proceeds
        losses = exp2.train_iteration(feats)
        assert np.isfinite(float(losses["d_loss"]))
        assert int(exp2.gen_state.step) == restored + 1

    def test_ragged_tail_batches_survive(self, tmp_path):
        """Epoch tails: an indivisible batch truncates to a full critic
        round; a batch smaller than n_critic pads by cycling — either way
        the run continues instead of aborting (code-review r2 finding)."""
        exp = make_experiment(tiny_config(tmp_path))  # n_critic=2
        fam = registry.get("wgan_gp")
        feats7 = fam.synthetic_data(7, exp.model_cfg, 0)
        losses = exp.train_iteration(feats7)  # 7 -> truncated to 6
        assert np.isfinite(float(losses["d_loss"]))
        feats1 = fam.synthetic_data(1, exp.model_cfg, 1)
        losses = exp.train_iteration(feats1)  # 1 -> padded to 2
        assert np.isfinite(float(losses["d_loss"]))
        with pytest.raises(ValueError):
            exp.train_iteration(np.zeros((0, 64), np.float32))

    def test_predictions_refused(self, tmp_path):
        exp = make_experiment(tiny_config(tmp_path))
        with pytest.raises(ValueError):
            exp.export_predictions(None, 1)

    def test_flops_cost_counts_all_critic_steps(self, tmp_path):
        """XLA's cost_analysis counts a lax.scan body ONCE regardless of trip
        count (round-4 finding), so flops_per_iteration must multiply the
        critic-round cost by n_critic — doubling n_critic (at the same
        per-step batch) must roughly add the critic cost again, not leave
        the total flat. Without the fix, every WGAN MFU reads ~n_critic×
        too low."""
        from gan_deeplearning4j_tpu.harness import make_experiment

        flops = {}
        for n in (2, 4):
            exp = make_experiment(tiny_config(
                tmp_path, n_critic=n, batch_size_train=4 * n,
                batch_size_pred=4 * n,
            ))
            flops[n] = exp.flops_per_iteration()
        assert flops[2] and flops[4]
        ratio = flops[4] / flops[2]  # (4c+g)/(2c+g) ∈ (1, 2)
        assert 1.3 < ratio < 2.05, ratio
        exp = make_experiment(tiny_config(tmp_path))
        imgs = exp.sample(4)
        assert imgs.shape == (4, 8, 8, 1)


class TestWganCli:
    def test_main_wgan_family(self, tmp_path, capsys):
        from gan_deeplearning4j_tpu.__main__ import main

        rc = main([
            "--model-family", "wgan_gp",
            "--height", "8", "--width", "8", "--channels", "1",
            "--num-features", "64", "--z-size", "4",
            "--batch-size-train", "8", "--batch-size-pred", "8",
            "--n-critic", "2", "--num-iterations", "1", "--latent-grid", "2",
            "--data-dir", str(tmp_path / "data"),
            "--output-dir", str(tmp_path / "out"),
            "--save-models", "false",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Manifold image:" in out  # PNG rendered without a classifier
        assert (tmp_path / "out" / "DCGAN_Generated_Images.png").exists()
