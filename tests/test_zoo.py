"""zoo/ — the manifest-driven model zoo (docs/ZOO.md).

Four claims under test, each against its real seam:

- **manifest round-trip** — one ScenarioManifest survives
  serializer → ``serving.json`` ``"zoo"`` block → serving engine, and the
  validation encodes the true architectural constraints (WGAN-GP's
  power-of-two stem, the queued wgan+class pair, dataset-native
  resolution) rather than wishful ones.
- **conditional serving** — ``POST /v1/sample?class=k`` is bit-exact
  against the un-staged host path on the same latent+one-hot rows for
  EVERY class, and the error contract (bare latent rows, out-of-range
  class, ``?class`` on a non-sample kind or an unconditional bundle)
  fails with 400s, never silence.
- **WGAN-GP supervisor resume** — continuing N rounds in-process and
  replaying the same N rounds from a checkpoint produce bit-identical
  states (the fold_in-per-round key schedule is step-derived, not
  instance-state).
- **streaming equivalence** — the double-buffered streaming iterator is
  byte-identical to the in-memory iterator at matched seed, across
  epochs and through the ragged tail.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import ArrayDataSetIterator
from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.serving import InferenceService, ServingEngine
from gan_deeplearning4j_tpu.utils import write_model
from gan_deeplearning4j_tpu.zoo import (
    DATASET_SHAPES,
    ScenarioManifest,
    scenario_from_bundle,
    scenario_from_config,
)
from gan_deeplearning4j_tpu.zoo.streaming import (
    StreamingDataSetIterator,
    array_source,
    npz_source,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, CLASSES, FEAT = 3, 4, 6


# ===========================================================================
# the scenario manifest
# ===========================================================================

class TestScenarioManifest:
    def test_round_trip_through_dict(self):
        for arch, cond, dataset in (
            ("dcgan", "none", "mnist"),
            ("dcgan", "class", "fashion_mnist"),
            ("dcgan", "none", "cifar_shaped"),
            ("wgan_gp", "none", "cifar_shaped"),
        ):
            scn = ScenarioManifest(
                architecture=arch, conditioning=cond, dataset=dataset,
                resolution=DATASET_SHAPES[dataset][0])
            assert ScenarioManifest.from_dict(scn.to_dict()) == scn

    def test_round_trip_through_experiment_config(self):
        scn = ScenarioManifest(
            architecture="dcgan", conditioning="class", dataset="mnist",
            resolution=28, num_classes=10, z_size=4)
        cfg = scn.experiment_config(seed=3)
        assert cfg.model_family == "mnist"
        assert cfg.conditioning == "class" and cfg.dataset == "mnist"
        assert (cfg.height, cfg.width, cfg.channels) == (28, 28, 1)
        assert scenario_from_config(cfg) == scn

    def test_family_mapping(self):
        assert ScenarioManifest(dataset="mnist").family_name == "mnist"
        assert ScenarioManifest(
            dataset="fashion_mnist").family_name == "mnist"
        assert ScenarioManifest(
            dataset="cifar_shaped", resolution=32).family_name == "image"
        assert ScenarioManifest(
            architecture="wgan_gp", dataset="cifar_shaped",
            resolution=32).family_name == "wgan_gp"

    def test_sample_input_width_includes_embedding(self):
        scn = ScenarioManifest(conditioning="class", num_classes=7, z_size=5)
        assert scn.sample_input_width == 12
        assert ScenarioManifest(z_size=5).sample_input_width == 5

    def test_rejections_encode_real_constraints(self):
        with pytest.raises(ValueError):
            ScenarioManifest(architecture="stylegan")
        with pytest.raises(ValueError):
            ScenarioManifest(dataset="imagenet")
        with pytest.raises(ValueError):  # resolution is not a free axis
            ScenarioManifest(dataset="mnist", resolution=32)
        with pytest.raises(ValueError):  # power-of-two stem
            ScenarioManifest(architecture="wgan_gp", dataset="mnist")
        with pytest.raises(ValueError):  # queued pair
            ScenarioManifest(
                architecture="wgan_gp", conditioning="class",
                dataset="cifar_shaped", resolution=32)
        with pytest.raises(ValueError):
            ScenarioManifest(conditioning="class", num_classes=1)

    def test_scenario_from_config_shape_guard(self):
        # a tiny test config claiming dataset='mnist' at 8x8 must NOT get
        # a zoo block: an honest manifest never declares a dataset whose
        # native shape the model doesn't have
        scn = ScenarioManifest(dataset="mnist")
        cfg = scn.experiment_config(seed=1)
        import dataclasses

        tiny = dataclasses.replace(
            cfg, height=8, width=8, channels=1, num_features=64)
        assert scenario_from_config(tiny) is None
        assert scenario_from_config(cfg) == scn

    def test_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError):
            ScenarioManifest.from_dict({"architecture": "dcgan",
                                        "flavor": "spicy"})

    def test_config_validation_matches_manifest(self):
        # config.py enforces the same queued pair server-side
        from gan_deeplearning4j_tpu.harness import ExperimentConfig

        with pytest.raises(ValueError):
            ExperimentConfig(
                model_family="wgan_gp", conditioning="class",
                height=32, width=32, channels=3, num_features=3072,
                batch_size_train=10, n_critic=5,
            ).validate()
        with pytest.raises(ValueError):
            ExperimentConfig(conditioning="sinusoidal").validate()


# ===========================================================================
# conditional serving: ?class=k parity + the 400 contract
# ===========================================================================

def _tiny_conditional_generator():
    """Generator taking [z | one-hot] (width Z+CLASSES) — the serving
    shape a conditional trainer publishes, minus the training time."""
    b = GraphBuilder(GraphConfig(seed=11))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z + CLASSES))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    return b.build()


def _scenario_dict():
    return ScenarioManifest(
        architecture="dcgan", conditioning="class", dataset="mnist",
        resolution=28, num_classes=CLASSES, z_size=Z).to_dict()


@pytest.fixture(scope="module")
def conditional_engine(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("zoo_cond")
    gen = _tiny_conditional_generator()
    gen_path = str(tmp / "gen.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    eng = ServingEngine.from_checkpoints(
        generator=gen_path, buckets=(1, 4), scenario=_scenario_dict())
    eng.warmup()
    return eng


class TestConditionalServing:
    def test_engine_reads_scenario(self, conditional_engine):
        eng = conditional_engine
        assert eng.conditional and eng.class_count == CLASSES
        assert eng.input_width("sample") == Z + CLASSES
        assert eng.latent_width("sample") == Z

    def test_declared_width_must_match_generator(self, tmp_path):
        gen = _tiny_conditional_generator()
        gen_path = str(tmp_path / "gen.zip")
        write_model(gen_path, gen, gen.init(), save_updater=False)
        bad = dict(_scenario_dict(), z_size=Z + 1)
        with pytest.raises(ValueError, match="disagree"):
            ServingEngine.from_checkpoints(
                generator=gen_path, buckets=(1,), scenario=bad)

    def test_per_class_parity_vs_run_host(self, conditional_engine):
        eng = conditional_engine
        svc = InferenceService(eng, warmup=False)
        rng = np.random.default_rng(5)
        try:
            for k in range(CLASSES):
                z = rng.random((3, Z), dtype=np.float32) * 2 - 1
                status, body = svc.handle(
                    "POST", f"/v1/sample?class={k}", {"data": z.tolist()})
                assert status == 200, body
                staged = np.asarray(body["data"], dtype=np.float32)
                onehot = np.zeros((3, CLASSES), dtype=np.float32)
                onehot[:, k] = 1.0
                host = eng.run_host(
                    "sample", np.concatenate([z, onehot], axis=1))
                np.testing.assert_array_equal(staged, np.asarray(host))
        finally:
            svc.close()

    def test_full_width_rows_still_served_without_class(
            self, conditional_engine):
        # the mux pinned-probe / parity-oracle path: callers that build
        # the one-hot themselves keep working without ?class=
        svc = InferenceService(conditional_engine, warmup=False)
        rows = np.zeros((2, Z + CLASSES), dtype=np.float32)
        rows[:, Z] = 1.0
        try:
            status, body = svc.handle(
                "POST", "/v1/sample", {"data": rows.tolist()})
            assert status == 200 and len(body["data"]) == 2
        finally:
            svc.close()

    def test_error_contract(self, conditional_engine):
        svc = InferenceService(conditional_engine, warmup=False)
        z = np.zeros((2, Z), dtype=np.float32)
        try:
            # bare latent-width rows: 400 with a pointer to ?class=
            status, body = svc.handle(
                "POST", "/v1/sample", {"data": z.tolist()})
            assert status == 400 and "class" in body["error"]
            # out-of-range class
            status, _ = svc.handle(
                "POST", f"/v1/sample?class={CLASSES}", {"data": z.tolist()})
            assert status == 400
            status, _ = svc.handle(
                "POST", "/v1/sample?class=-1", {"data": z.tolist()})
            assert status == 400
            # non-integer class
            status, _ = svc.handle(
                "POST", "/v1/sample?class=seven", {"data": z.tolist()})
            assert status == 400
        finally:
            svc.close()

    def test_unconditional_bundle_rejects_class(self, tmp_path):
        b = GraphBuilder(GraphConfig(seed=12))
        b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
        b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
        b.add_layer(
            "g_out",
            OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
            "g_dense_1",
        )
        b.set_outputs("g_out")
        gen = b.build()
        gen_path = str(tmp_path / "gen.zip")
        write_model(gen_path, gen, gen.init(), save_updater=False)
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, buckets=(1, 4))
        eng.warmup()
        svc = InferenceService(eng, warmup=False)
        z = np.zeros((1, Z), dtype=np.float32)
        try:
            status, body = svc.handle(
                "POST", "/v1/sample?class=1", {"data": z.tolist()})
            assert status == 400 and "conditional" in body["error"]
            # and plain sampling is untouched
            status, _ = svc.handle(
                "POST", "/v1/sample", {"data": z.tolist()})
            assert status == 200
        finally:
            svc.close()

    def test_healthz_names_scenario(self, conditional_engine):
        svc = InferenceService(conditional_engine, warmup=False)
        try:
            status, body = svc.handle("GET", "/healthz")
            assert status == 200
            assert body["scenario"]["conditioning"] == "class"
            assert body["scenario"]["dataset"] == "mnist"
        finally:
            svc.close()

    def test_canary_gate_fails_closed_on_dataset_mismatch(
            self, conditional_engine):
        from gan_deeplearning4j_tpu.deploy.canary import CanaryGate

        reals = np.random.default_rng(1).random((8, FEAT))
        probe = lambda engine: {"fid": 1.0, "accuracy": None}  # noqa: E731
        gate = CanaryGate(reals, dataset="fashion_mnist", probe=probe)
        decision = gate.evaluate(conditional_engine, conditional_engine)
        assert not decision.passed and "fashion_mnist" in decision.reason
        # same dataset (or an unset gate): the probe path runs
        assert CanaryGate(reals, dataset="mnist", probe=probe).evaluate(
            conditional_engine, conditional_engine).passed
        assert CanaryGate(reals, probe=probe).evaluate(
            conditional_engine, conditional_engine).passed

    def test_canary_probe_supplies_onehot_for_conditional(
            self, conditional_engine):
        # the default probe draws BASE-z latents and the gate appends a
        # cycling one-hot — the probe must run (and score finitely) on a
        # conditional engine without a width error
        from gan_deeplearning4j_tpu.deploy.canary import CanaryGate

        reals = np.random.default_rng(2).random((16, FEAT))
        gate = CanaryGate(reals, num_samples=8)
        result = gate.probe(conditional_engine)
        assert np.isfinite(result["fid"])


# ===========================================================================
# the bundle round trip: serializer -> serving.json -> engine
# ===========================================================================

class TestBundleRoundTrip:
    def test_conditional_mnist_bundle_round_trips(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import GanExperiment

        scn = ScenarioManifest(
            architecture="dcgan", conditioning="class", dataset="mnist",
            resolution=28, num_classes=10, z_size=4)
        exp = GanExperiment(scn.experiment_config(seed=9))
        bundle = str(tmp_path / "bundle")
        exp.publish_for_serving(bundle)
        with open(os.path.join(bundle, "serving.json")) as fh:
            manifest = json.load(fh)
        assert manifest["zoo"] == scn.to_dict()
        assert manifest["z_size"] == 4  # base z, not the widened input
        assert scenario_from_bundle(bundle) == scn
        eng = ServingEngine.from_bundle(bundle, buckets=(2,))
        assert eng.conditional and eng.class_count == 10
        assert eng.input_width("sample") == 14
        assert eng.latent_width("sample") == 4
        # one staged-vs-host spot check through the real bundle
        eng.warmup()
        svc = InferenceService(eng, warmup=False)
        z = np.random.default_rng(3).random((2, 4), dtype=np.float32)
        try:
            status, body = svc.handle(
                "POST", "/v1/sample?class=7", {"data": z.tolist()})
            assert status == 200
            onehot = np.zeros((2, 10), dtype=np.float32)
            onehot[:, 7] = 1.0
            host = eng.run_host(
                "sample", np.concatenate([z, onehot], axis=1))
            np.testing.assert_array_equal(
                np.asarray(body["data"], dtype=np.float32),
                np.asarray(host))
        finally:
            svc.close()

    def test_legacy_shape_publishes_without_zoo_block(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import (
            ExperimentConfig,
            GanExperiment,
        )

        cfg = ExperimentConfig(
            model_family="tabular", num_features=12, z_size=4,
            batch_size_train=8, batch_size_pred=8,
        )
        exp = GanExperiment(cfg)
        bundle = str(tmp_path / "bundle")
        exp.publish_for_serving(bundle)
        with open(os.path.join(bundle, "serving.json")) as fh:
            manifest = json.load(fh)
        assert "zoo" not in manifest
        assert scenario_from_bundle(bundle) is None
        eng = ServingEngine.from_bundle(bundle, buckets=(2,))
        assert not eng.conditional and eng.scenario is None


# ===========================================================================
# WGAN-GP supervisor resume
# ===========================================================================

def _wgan_config(tmp_path, **overrides):
    from gan_deeplearning4j_tpu.harness import ExperimentConfig

    base = dict(
        model_family="wgan_gp",
        height=8, width=8, channels=1, num_features=64, z_size=4,
        batch_size_train=8, batch_size_pred=8, n_critic=2,
        num_iterations=1, latent_grid=2,
        data_dir=str(tmp_path / "data"), output_dir=str(tmp_path / "out"),
        save_models=True,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


class TestWganSupervisorResume:
    def test_resume_is_bit_exact(self, tmp_path):
        """Checkpoint after round 1, keep training rounds 2-3 in-process;
        a fresh experiment restoring the checkpoint and replaying rounds
        2-3 on the same data must land on bit-identical critic AND
        generator states — the per-round key is folded from the gen step,
        so resume replays the exact key schedule. Digested through the
        supervisor's restore-verification contract."""
        from gan_deeplearning4j_tpu.harness.wgan_experiment import (
            WganGpExperiment,
        )
        from gan_deeplearning4j_tpu.resilience.supervisor import (
            TrainingSupervisor,
        )

        rng = np.random.default_rng(17)
        rounds = [rng.random((8, 64), dtype=np.float32) for _ in range(3)]

        cfg = _wgan_config(tmp_path)
        exp = WganGpExperiment(cfg)
        exp.train_iteration(rounds[0])
        exp.save_models()
        for feats in rounds[1:]:
            exp.train_iteration(feats)
        want = TrainingSupervisor.state_digests(exp)

        exp2 = WganGpExperiment(cfg)
        restored = exp2.load_models()
        assert restored == 1
        for feats in rounds[1:]:
            exp2.train_iteration(feats)
        assert TrainingSupervisor.state_digests(exp2) == want
        assert int(exp2.gen_state.step) == int(exp.gen_state.step) == 3

    def test_divergent_replay_changes_digest(self, tmp_path):
        # the digest is sensitive: replaying DIFFERENT data from the same
        # checkpoint must not collide (guards against a digest that
        # ignores params)
        from gan_deeplearning4j_tpu.harness.wgan_experiment import (
            WganGpExperiment,
        )
        from gan_deeplearning4j_tpu.resilience.supervisor import (
            TrainingSupervisor,
        )

        rng = np.random.default_rng(18)
        a = rng.random((8, 64), dtype=np.float32)
        b = rng.random((8, 64), dtype=np.float32)
        cfg = _wgan_config(tmp_path)
        exp = WganGpExperiment(cfg)
        exp.train_iteration(a)
        exp.save_models()
        exp.train_iteration(a)
        want = TrainingSupervisor.state_digests(exp)
        exp2 = WganGpExperiment(cfg)
        exp2.load_models()
        exp2.train_iteration(b)
        assert TrainingSupervisor.state_digests(exp2) != want


# ===========================================================================
# streaming equivalence
# ===========================================================================

class TestStreamingIterator:
    def test_bit_identical_to_in_memory_iterator(self):
        rng = np.random.default_rng(0)
        x = rng.random((103, 12))  # ragged tail: 103 % 16 != 0
        y = (np.arange(103) % 10).astype(np.float32)
        source, n = array_source(x, y)
        stream = StreamingDataSetIterator(
            source, n, batch_size=16, shuffle=True, seed=7, block_batches=2)
        memory = ArrayDataSetIterator(x, y, batch_size=16, shuffle=True,
                                      seed=7)
        try:
            for _ in range(3):  # epochs, each a fresh permutation
                while memory.has_next():
                    a, s = memory.next(), stream.next()
                    np.testing.assert_array_equal(
                        np.asarray(a.features), np.asarray(s.features))
                    np.testing.assert_array_equal(
                        np.asarray(a.labels), np.asarray(s.labels))
                assert not stream.has_next()
                memory.reset()
                stream.reset()
        finally:
            stream.close()

    def test_unshuffled_and_unlabeled(self):
        x = np.arange(40, dtype=np.float32).reshape(10, 4)
        source, n = array_source(x)
        stream = StreamingDataSetIterator(source, n, batch_size=4,
                                          block_batches=1)
        got = []
        try:
            while stream.has_next():
                batch = stream.next()
                assert batch.labels is None
                got.append(np.asarray(batch.features))
        finally:
            stream.close()
        np.testing.assert_array_equal(np.concatenate(got), x)

    def test_npz_source(self, tmp_path):
        x = np.random.default_rng(2).random((9, 5)).astype(np.float32)
        y = np.arange(9, dtype=np.float32)
        path = str(tmp_path / "rows.npz")
        np.savez(path, features=x, labels=y)
        source, n = npz_source(path)
        assert n == 9
        feats, labs = source(np.array([2, 0, 7]))
        np.testing.assert_array_equal(feats, x[[2, 0, 7]])
        np.testing.assert_array_equal(labs, y[[2, 0, 7]])

    def test_drop_remainder(self):
        x = np.random.default_rng(3).random((10, 3))
        source, n = array_source(x)
        stream = StreamingDataSetIterator(source, n, batch_size=4,
                                          drop_remainder=True)
        sizes = []
        try:
            while stream.has_next():
                sizes.append(np.asarray(stream.next().features).shape[0])
        finally:
            stream.close()
        assert sizes == [4, 4]

    def test_rejects_bad_block(self):
        source, n = array_source(np.zeros((4, 2)))
        with pytest.raises(ValueError):
            StreamingDataSetIterator(source, n, block_batches=0)

    def test_trains_through_streaming_window(self, tmp_path):
        """The data-plane swap claim: a conditional training window pulled
        through the streaming iterator is the same (K, B, F) array an
        in-memory pull produces — so training through it is bit-identical
        by construction."""
        from gan_deeplearning4j_tpu.zoo.datasets import load_dataset

        (x, y), _ = load_dataset("mnist", num_train=64, num_test=8, seed=4)
        source, n = array_source(x, y)
        stream = StreamingDataSetIterator(source, n, batch_size=8,
                                          shuffle=True, seed=5,
                                          block_batches=2)
        memory = ArrayDataSetIterator(x, y, batch_size=8, shuffle=True,
                                      seed=5)
        try:
            for _ in range(2):
                np.testing.assert_array_equal(
                    np.asarray(stream.next().features),
                    np.asarray(memory.next().features))
        finally:
            stream.close()


# ===========================================================================
# the drill, end to end (campaign-gated; slow tier)
# ===========================================================================

class TestZooDrill:
    @pytest.mark.slow
    def test_smoke_drill_passes(self, tmp_path):
        out = str(tmp_path / "zoo_drill.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "zoo_drill.py"),
             "--smoke", "--output", out],
            capture_output=True, text=True, timeout=580,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "GDT_COMPILATION_CACHE": "off"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(out) as fh:
            payload = json.load(fh)
        assert payload["ok"] and all(payload["invariants"].values())
        assert payload["results"]["conditional"]["parity_classes"] == 10
