"""telemetry/alerts.py — the fleet alerting & anomaly-detection plane.

Covers the declarative rule kinds (threshold / absence / burn /
anomaly), the pending→firing→resolved lifecycle with per-direction
hysteresis, the fail-closed three-valued evaluation (NaN / empty
baselines / missing series may reach pending, never firing — and never
resolve a firing alert), exemplar capture, sinks, the prom ``ALERTS``
rendering, the default rule packs, and the registry's series-removal
seam the member gauges rely on. Everything here is stdlib-only and
clock-injected — no sleeps, no sockets except the webhook test's local
receiver.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gan_deeplearning4j_tpu.telemetry.alerts import (
    AlertManager,
    AlertRule,
    ExemplarStore,
    WebhookSink,
    default_fleet_rules,
    default_mux_rules,
)
from gan_deeplearning4j_tpu.telemetry.registry import get_registry


def gauge_snap(name, value, labels=None):
    return {name: {"type": "gauge", "help": "",
                   "series": [{"labels": labels or {}, "value": value}]}}


def manager(rules, **kw):
    clockbox = kw.pop("clockbox", [0.0])

    def clock():
        clockbox[0] += 1.0
        return clockbox[0]

    return AlertManager(rules, clock=clock, wall_clock=clock, **kw), clockbox


def states_for(mgr, name="r"):
    active = [e for e in mgr.active() if e["alert"] == name]
    return active[0]["state"] if active else "inactive"


THRESHOLD = dict(name="r", kind="threshold", metric="g", op=">", bound=5.0,
                 for_ticks=2, keep_firing_ticks=2, resolved_hold_ticks=2)


# ===========================================================================
# lifecycle
# ===========================================================================

class TestLifecycle:
    def test_full_cycle_with_hysteresis(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        seen = []
        for v in [1, 9, 9, 9, 1, 1, 1, 1]:
            mgr.evaluate(gauge_snap("g", v))
            seen.append(states_for(mgr))
        # 1 breach = pending (not firing: for_ticks=2); 2 clears to leave
        # firing; resolved visible for resolved_hold_ticks then inactive
        assert seen == ["inactive", "pending", "firing", "firing",
                        "firing", "resolved", "resolved", "inactive"]

    def test_flap_cannot_reach_firing(self):
        # breach/clear alternation never accumulates for_ticks=2
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for v in [9, 1, 9, 1, 9, 1, 9, 1]:
            mgr.evaluate(gauge_snap("g", v))
            assert states_for(mgr) in ("pending", "inactive")

    def test_breach_while_firing_rearms_the_resolve_hysteresis(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for v in [9, 9]:
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"
        # one clear, then a breach: the clear streak resets, still firing
        for v in [1, 9, 1]:
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"

    def test_transitions_counted_per_alertname_and_state(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for v in [9, 9, 1, 1]:
            mgr.evaluate(gauge_snap("g", v))
        fam = {tuple(sorted(labels.items())): series.value
               for labels, series in get_registry()
               ._families["fleet_alerts_total"].series()}
        assert fam[(("alertname", "r"), ("state", "pending"))] == 1
        assert fam[(("alertname", "r"), ("state", "firing"))] == 1
        assert fam[(("alertname", "r"), ("state", "resolved"))] == 1

    def test_incident_ring_bounded_and_ordered(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)], max_incidents=4)
        for v in [9, 9, 1, 1, 9, 9, 1, 1]:
            mgr.evaluate(gauge_snap("g", v))
        incidents = mgr.snapshot()["incidents"]
        assert len(incidents) == 4  # bounded, newest kept
        assert [i["to"] for i in incidents][-1] in ("resolved", "inactive")

    def test_per_series_instances_with_labels(self):
        # one rule over a labeled family fans out per series
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        snap = {"g": {"type": "gauge", "help": "", "series": [
            {"labels": {"worker": "w0"}, "value": 9.0},
            {"labels": {"worker": "w1"}, "value": 1.0},
        ]}}
        mgr.evaluate(snap)
        mgr.evaluate(snap)
        active = mgr.active()
        assert [(e["labels"], e["state"]) for e in active] == [
            ({"worker": "w0"}, "firing")]

    def test_arm_on_first_clear_suppresses_boot_breaches(self):
        rule = AlertRule(**{**THRESHOLD, "op": "<", "bound": 1.0,
                            "arm_on_first_clear": True})
        mgr, _ = manager([rule])
        # "down" from the first evaluation — boot, not a regression
        for _ in range(5):
            mgr.evaluate(gauge_snap("g", 0.0))
        assert states_for(mgr) == "inactive"
        mgr.evaluate(gauge_snap("g", 1.0))  # first healthy eval arms
        for _ in range(2):
            mgr.evaluate(gauge_snap("g", 0.0))
        assert states_for(mgr) == "firing"


# ===========================================================================
# fail-closed evaluation
# ===========================================================================

class TestFailClosed:
    def test_nan_reaches_pending_never_firing(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for _ in range(10):
            mgr.evaluate(gauge_snap("g", float("nan")))
            assert states_for(mgr) == "pending"

    def test_none_value_reads_as_nan(self):
        # a JSON-sanitized snapshot (null for NaN) evaluates identically
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        mgr.evaluate(gauge_snap("g", None))
        assert states_for(mgr) == "pending"

    def test_no_data_never_resolves_a_firing_alert(self):
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for v in [9, 9]:
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"
        for _ in range(10):
            mgr.evaluate(gauge_snap("g", float("nan")))
            assert states_for(mgr) == "firing"

    def test_data_gap_resets_the_clear_streak(self):
        # review-caught: keep_firing_ticks means CONSECUTIVE clears —
        # two clears separated by a blind spot (the scrape wedging
        # during the very incident being alerted on) must not sum up
        # and resolve a live breach
        mgr, _ = manager([AlertRule(**THRESHOLD)])  # keep_firing_ticks=2
        for v in [9, 9]:
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"
        for v in [1, float("nan"), 1]:  # clear, gap, clear — not 2 in a row
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"
        mgr.evaluate(gauge_snap("g", 1))  # the second CONSECUTIVE clear
        assert states_for(mgr) == "resolved"

    def test_vanished_series_resolves_after_hysteresis(self):
        # the series being GONE (a retired worker) is not an ongoing
        # breach: firing resolves after keep_firing_ticks unobserved
        mgr, _ = manager([AlertRule(**THRESHOLD)])
        for v in [9, 9]:
            mgr.evaluate(gauge_snap("g", v))
        assert states_for(mgr) == "firing"
        empty = {"g": {"type": "gauge", "help": "", "series": []}}
        mgr.evaluate(empty)
        assert states_for(mgr) == "firing"
        mgr.evaluate(empty)
        assert states_for(mgr) == "resolved"

    def test_anomaly_empty_baseline_pending_never_firing(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="anomaly", metric="h", field="p99",
            window=50, min_points=10, for_ticks=1)])
        for _ in range(5):
            mgr.evaluate({"h": {"type": "histogram", "help": "", "series": [
                {"labels": {}, "count": 1, "sum": 1.0, "p99": 0.01}]}})
            assert states_for(mgr) == "pending"

    def test_burn_nan_window_pending_never_firing(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="burn", metric="b", objective="availability",
            for_ticks=1)])
        snap = {"b": {"type": "gauge", "help": "", "series": [
            {"labels": {"objective": "availability", "window": "fast"},
             "value": 5.0},
            {"labels": {"objective": "availability", "window": "slow"},
             "value": float("nan")},
        ]}}
        for _ in range(3):
            mgr.evaluate(snap)
            assert states_for(mgr) == "pending"


# ===========================================================================
# rule kinds
# ===========================================================================

class TestRuleKinds:
    def test_absence_fires_on_missing_series(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="absence", metric="g",
            labels={"worker": "w0"}, for_ticks=2, keep_firing_ticks=1)])
        mgr.evaluate({})
        mgr.evaluate({})
        assert states_for(mgr) == "firing"
        mgr.evaluate(gauge_snap("g", 1.0, labels={"worker": "w0"}))
        assert states_for(mgr) == "resolved"

    def test_threshold_rate_on_counter(self):
        mgr, clockbox = manager([AlertRule(
            name="r", kind="threshold", metric="c", rate=True,
            op=">", bound=0.0, for_ticks=1, keep_firing_ticks=1)])
        counter = lambda v: {"c": {"type": "counter", "help": "",  # noqa: E731
                                   "series": [{"labels": {}, "value": v}]}}
        mgr.evaluate(counter(0))      # first point: rate undefined
        assert states_for(mgr) == "pending"
        mgr.evaluate(counter(0))      # rate 0 — clear
        assert states_for(mgr) == "inactive"
        mgr.evaluate(counter(3))      # climbing
        assert states_for(mgr) == "firing"

    def test_threshold_rate_counter_reset_is_undefined(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="threshold", metric="c", rate=True,
            op=">", bound=0.0, for_ticks=1, keep_firing_ticks=1)])
        counter = lambda v: {"c": {"type": "counter", "help": "",  # noqa: E731
                                   "series": [{"labels": {}, "value": v}]}}
        mgr.evaluate(counter(10))
        mgr.evaluate(counter(11))
        assert states_for(mgr) == "firing"
        # a restarted process resets the counter: dv < 0 is undefined,
        # not negative traffic — and no data never resolves
        mgr.evaluate(counter(0))
        assert states_for(mgr) == "firing"

    def test_burn_requires_both_windows(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="burn", metric="b", objective="availability",
            burn_threshold=1.0, for_ticks=1, keep_firing_ticks=1)])
        snap = lambda fast, slow: {"b": {  # noqa: E731
            "type": "gauge", "help": "", "series": [
                {"labels": {"objective": "availability",
                            "window": "fast"}, "value": fast},
                {"labels": {"objective": "availability",
                            "window": "slow"}, "value": slow}]}}
        mgr.evaluate(snap(5.0, 0.1))   # fast only: the blip case
        assert states_for(mgr) == "inactive"
        mgr.evaluate(snap(5.0, 2.0))   # both: the page case
        assert states_for(mgr) == "firing"

    def test_burn_groups_per_model(self):
        # the mux scoping: one rule, one instance per model label set
        mgr, _ = manager([AlertRule(
            name="r", kind="burn", metric="mux_slo_burn_rate",
            objective="availability", for_ticks=1)])
        series = []
        for model, fast, slow in (("a", 9, 9), ("b", 0.1, 0.1)):
            for window, value in (("fast", fast), ("slow", slow)):
                series.append({"labels": {"model": model,
                                          "objective": "availability",
                                          "window": window},
                               "value": value})
        mgr.evaluate({"mux_slo_burn_rate": {"type": "gauge", "help": "",
                                            "series": series}})
        active = mgr.active()
        assert [(e["labels"]["model"], e["state"]) for e in active] == [
            ("a", "firing")]

    def test_burn_ignores_other_objective(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="burn", metric="b", objective="availability",
            for_ticks=1)])
        mgr.evaluate({"b": {"type": "gauge", "help": "", "series": [
            {"labels": {"objective": "latency", "window": "fast"},
             "value": 9.0},
            {"labels": {"objective": "latency", "window": "slow"},
             "value": 9.0}]}})
        assert mgr.active() == []

    def test_anomaly_fires_on_drift_and_resolves(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="anomaly", metric="h", field="p99",
            window=50, min_points=5, z_max=6.0, for_ticks=2,
            keep_firing_ticks=2, mad_floor_frac=0.05)])
        hist = lambda p99: {"h": {"type": "histogram", "help": "",  # noqa: E731
                                  "series": [{"labels": {}, "count": 9,
                                              "sum": 1.0, "p99": p99}]}}
        seen = []
        for v in [0.01, 0.011, 0.01, 0.012, 0.01, 0.011,
                  0.2, 0.2, 0.2, 0.01, 0.011]:
            mgr.evaluate(hist(v))
            seen.append(states_for(mgr))
        assert seen[6:9] == ["pending", "firing", "firing"]
        assert seen[-1] == "resolved"

    def test_anomaly_baseline_not_contaminated_by_breaches(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="anomaly", metric="h", field="p99",
            window=50, min_points=5, z_max=6.0, for_ticks=1,
            keep_firing_ticks=1)])
        hist = lambda p99: {"h": {"type": "histogram", "help": "",  # noqa: E731
                                  "series": [{"labels": {}, "count": 9,
                                              "sum": 1.0, "p99": p99}]}}
        for v in [0.01, 0.011, 0.01, 0.012, 0.01]:
            mgr.evaluate(hist(v))
        for _ in range(30):  # a long incident
            mgr.evaluate(hist(0.5))
        state = list(mgr._states["r"].values())[0]
        assert max(state.baseline) < 0.1  # anomalous points never joined
        mgr.evaluate(hist(0.01))  # recovery reads against the CLEAN base
        assert states_for(mgr) == "resolved"

    def test_anomaly_mad_floor_abs_for_zero_median(self):
        # queue-depth-shaped series: median 0 + a blip of 1 must not be
        # an infinite z
        mgr, _ = manager([AlertRule(
            name="r", kind="anomaly", metric="g", field=None,
            window=50, min_points=5, z_max=8.0, mad_floor_abs=1.0,
            for_ticks=1, keep_firing_ticks=1)])
        for _ in range(6):
            mgr.evaluate(gauge_snap("g", 0.0))
        mgr.evaluate(gauge_snap("g", 2.0))
        assert states_for(mgr) in ("inactive", "pending")
        mgr.evaluate(gauge_snap("g", 50.0))
        assert states_for(mgr) == "firing"

    def test_gauge_anomaly_reads_value_when_field_none(self):
        mgr, _ = manager([AlertRule(
            name="r", kind="anomaly", metric="g", field=None,
            window=50, min_points=3, z_max=6.0, for_ticks=1,
            keep_firing_ticks=1)])
        for v in [1.0, 1.1, 1.0, 0.9]:
            mgr.evaluate(gauge_snap("g", v))
        mgr.evaluate(gauge_snap("g", 100.0))
        assert states_for(mgr) == "firing"


# ===========================================================================
# exemplars, annotations, sinks, surfaces
# ===========================================================================

class TestEvidenceAndSurfaces:
    def test_firing_captures_matching_exemplars(self):
        store = ExemplarStore()
        store.record("worker_failure", "tid-1", worker="w0", pid=11)
        store.record("worker_failure", "tid-2", worker="w1", pid=22)
        store.record("worker_failure", "tid-3", worker="w0", pid=11)
        mgr, _ = manager([AlertRule(
            **{**THRESHOLD, "op": "<", "bound": 1.0,
               "exemplar_category": "worker_failure",
               "for_ticks": 1})], exemplars=store)
        mgr.evaluate(gauge_snap("g", 0.0, labels={"worker": "w0"}))
        [entry] = mgr.active()
        ids = [e["trace_id"] for e in entry["exemplars"]]
        assert ids == ["tid-3", "tid-1"]  # newest first, w1's excluded

    def test_exemplar_store_bounded(self):
        store = ExemplarStore(per_category=3)
        for i in range(10):
            store.record("latency", f"t{i}")
        assert [e["trace_id"] for e in store.recent("latency", k=99)] == [
            "t9", "t8", "t7"]

    def test_annotate_hook_runs_at_pending(self):
        mgr, _ = manager([AlertRule(
            **{**THRESHOLD, "for_ticks": 1,
               "annotate": lambda labels: {"pid": 4242}})])
        mgr.evaluate(gauge_snap("g", 9.0))
        [entry] = mgr.active()
        assert entry["annotations"] == {"pid": 4242}
        assert mgr.snapshot()["incidents"][0]["annotations"] == {"pid": 4242}

    def test_sink_receives_transitions_and_bugs_are_contained(self):
        seen = []

        def bad_sink(record):
            raise RuntimeError("sink bug")

        mgr, _ = manager([AlertRule(**{**THRESHOLD, "for_ticks": 1})],
                         sinks=(bad_sink, seen.append))
        mgr.evaluate(gauge_snap("g", 9.0))
        assert [r["to"] for r in seen] == ["pending", "firing"]

    def test_prometheus_alerts_rendering(self):
        mgr, _ = manager([AlertRule(**{**THRESHOLD, "for_ticks": 1})])
        mgr.evaluate(gauge_snap("g", 9.0, labels={"worker": "w0"}))
        text = mgr.to_prometheus()
        assert '# TYPE ALERTS gauge' in text
        assert ('ALERTS{alertname="r",severity="page",state="firing",'
                'worker="w0"} 1') in text
        # resolved instances leave the prom surface
        for _ in range(2):
            mgr.evaluate(gauge_snap("g", 1.0, labels={"worker": "w0"}))
        assert "ALERTS{" not in mgr.to_prometheus()

    def test_snapshot_and_health_block_shapes(self):
        mgr, _ = manager([AlertRule(**{**THRESHOLD, "for_ticks": 1})])
        mgr.evaluate(gauge_snap("g", 9.0))
        snap = mgr.snapshot()
        assert snap["rules"][0]["name"] == "r"
        assert snap["counts"]["firing"] == 1
        assert json.dumps(snap)  # JSON-safe (no NaN leaks)
        block = mgr.health_block()
        assert block["ok"] is False
        assert block["firing"][0]["alert"] == "r"

    def test_webhook_sink_delivers_with_bounded_retry(self):
        hits = []

        class Hook(BaseHTTPRequestHandler):
            fail_first = [True]

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n))
                if self.fail_first[0]:
                    self.fail_first[0] = False
                    self.send_response(500)
                    self.end_headers()
                    return
                hits.append(body)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *args):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Hook)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            sink = WebhookSink(
                f"http://127.0.0.1:{srv.server_address[1]}/hook",
                timeout=2.0, retries=2, backoff_s=0.01)
            sink({"alert": "r", "to": "firing"})
            deadline = 50
            while not hits and deadline:
                deadline -= 1
                threading.Event().wait(0.1)
            assert hits and hits[0]["alert"] == "r"
            assert sink.sent == 1
            sink.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_webhook_thread_survives_non_oserror(self):
        # review-caught: a malformed URL raises ValueError from urlopen
        # — it must count as a failed delivery, not kill the daemon
        # thread (which would silently drop every FUTURE page)
        from gan_deeplearning4j_tpu.telemetry.alerts import WebhookSink

        sink = WebhookSink("localhost:9/hook",  # no scheme: ValueError
                           timeout=0.5, retries=0, backoff_s=0.0)
        try:
            sink({"alert": "a", "to": "firing"})
            deadline = 50
            while sink.failed < 1 and deadline:
                deadline -= 1
                threading.Event().wait(0.05)
            assert sink.failed == 1
            assert sink._thread.is_alive()  # the channel is still up
            sink({"alert": "b", "to": "firing"})
            deadline = 50
            while sink.failed < 2 and deadline:
                deadline -= 1
                threading.Event().wait(0.05)
            assert sink.failed == 2  # later records still processed
        finally:
            sink.close()

    def test_duplicate_rule_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            AlertManager([AlertRule(**THRESHOLD),
                          AlertRule(**THRESHOLD)])

    def test_rule_validation(self):
        with pytest.raises(ValueError, match="kind"):
            AlertRule(name="x", kind="nope", metric="m").validate()
        with pytest.raises(ValueError, match="bound"):
            AlertRule(name="x", kind="threshold", metric="m").validate()
        with pytest.raises(ValueError, match="for_ticks"):
            AlertRule(name="x", kind="absence", metric="m",
                      for_ticks=0).validate()
        with pytest.raises(ValueError, match="min_points"):
            AlertRule(name="x", kind="anomaly", metric="m", window=2,
                      min_points=8).validate()


# ===========================================================================
# default packs + evaluation over a real merged snapshot
# ===========================================================================

class TestDefaultPacks:
    def test_packs_validate_and_are_distinct(self):
        fleet = default_fleet_rules()
        mux = default_mux_rules()
        assert {r.name for r in fleet} >= {
            "worker_down", "scrape_stale", "slo_availability_burn",
            "brownout_latched", "spawn_failures_climbing",
            "latency_anomaly", "queue_pressure_anomaly"}
        assert {r.name for r in mux} == {"model_slo_burn",
                                         "model_queue_anomaly"}
        AlertManager(fleet)  # constructs (validates every rule)

    def test_evaluator_consumes_a_real_merged_snapshot(self):
        # shape compatibility with telemetry/aggregate.merge_snapshots:
        # the evaluator reads the fleet-scope payload unchanged
        from gan_deeplearning4j_tpu.telemetry.aggregate import (
            merge_snapshots,
        )

        part = {
            "fleet_member_routable": {
                "type": "gauge", "help": "",
                "series": [{"labels": {"worker": "w0"}, "value": 0.0}]},
        }
        merged = merge_snapshots({"router": part})
        rule = AlertRule(name="down", kind="threshold",
                         metric="fleet_member_routable", op="<",
                         bound=1.0, for_ticks=1, keep_firing_ticks=1)
        mgr, _ = manager([rule])
        mgr.evaluate(merged)
        [entry] = mgr.active()
        # the member's own worker label survived the merge (setdefault)
        assert entry["labels"]["worker"] == "w0"
        assert entry["state"] == "firing"


# ===========================================================================
# registry series removal (the member-gauge seam)
# ===========================================================================

class TestSeriesRemoval:
    def test_family_remove_drops_one_series(self):
        fam = get_registry().gauge("removal_g", "x",
                                   labelnames=("worker",))
        fam.labels(worker="w0").set(1.0)
        fam.labels(worker="w1").set(2.0)
        assert fam.remove(worker="w0") is True
        assert fam.remove(worker="w0") is False  # already gone
        assert [labels for labels, _ in fam.series()] == [{"worker": "w1"}]

    def test_remove_validates_labels(self):
        fam = get_registry().gauge("removal_g2", "x",
                                   labelnames=("worker",))
        with pytest.raises(ValueError):
            fam.remove(nope="x")
