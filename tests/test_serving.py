"""serving/ subsystem tests: bucket-ladder engine, micro-batcher policy
(coalescing, deadlines, backpressure), the in-process + HTTP service, the
publish→load round trip, and the serve_bench invariants (slow).

Engine tests use tiny dense graphs (millisecond compiles) — the serving
layer is model-agnostic, so the physics is identical to the MNIST stack the
bench drives. The fast service smoke below is the tier-1 acceptance item:
in-process service, 2 buckets, ~50 mixed requests, zero lost.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from gan_deeplearning4j_tpu.nn import (
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.serving import (
    InferenceService,
    MicroBatcher,
    ServingEngine,
    make_server,
)
from gan_deeplearning4j_tpu.utils import write_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Z, FEAT, CLASSES, HIDDEN = 4, 6, 3, 5


def tiny_generator():
    b = GraphBuilder(GraphConfig(seed=1))
    b.add_inputs("z").set_input_types(InputType.feed_forward(Z))
    b.add_layer("g_dense_1", DenseLayer(n_out=8), "z")
    b.add_layer(
        "g_out", OutputLayer(n_out=FEAT, activation="sigmoid", loss="xent"),
        "g_dense_1",
    )
    b.set_outputs("g_out")
    return b.build()


def tiny_classifier():
    b = GraphBuilder(GraphConfig(seed=2))
    b.add_inputs("x").set_input_types(InputType.feed_forward(FEAT))
    b.add_layer("feat_1", DenseLayer(n_out=HIDDEN), "x")
    b.add_layer(
        "cv_out",
        OutputLayer(n_out=CLASSES, activation="softmax", loss="mcxent"),
        "feat_1",
    )
    b.set_outputs("cv_out")
    return b.build()


@pytest.fixture(scope="module")
def checkpoints(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serving_ckpt")
    gen, cv = tiny_generator(), tiny_classifier()
    gen_path, cv_path = str(tmp / "gen.zip"), str(tmp / "cv.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    write_model(cv_path, cv, cv.init(), save_updater=False)
    return gen_path, cv_path


@pytest.fixture(scope="module")
def engine(checkpoints):
    gen_path, cv_path = checkpoints
    eng = ServingEngine.from_checkpoints(
        generator=gen_path, classifier=cv_path,
        buckets=(1, 8), feature_vertex="feat_1",
    )
    eng.warmup()
    return eng


class TestEngine:
    def test_kinds_and_widths(self, engine):
        assert set(engine.kinds) == {"sample", "classify", "features"}
        assert engine.input_width("sample") == Z
        assert engine.input_width("classify") == FEAT

    def test_padding_is_invisible(self, engine):
        """A size-5 request rides the 8-bucket; rows come back unpadded and
        equal to the unbatched forward (padding rows never leak)."""
        x = np.random.default_rng(0).random((5, FEAT), dtype=np.float32)
        out = engine.run("classify", x)
        assert out.shape == (5, CLASSES)
        np.testing.assert_allclose(
            out,
            np.asarray(engine.run("classify", np.concatenate([x, x]))[:5]),
            rtol=1e-5,
        )

    def test_compile_count_bounded_by_ladder(self, engine):
        """Mixed request sizes reuse the padded buckets — the serve-path
        recompile hazard the ladder exists to kill."""
        before = engine.compile_counts
        for n in (1, 2, 3, 5, 7, 8, 4, 6):
            engine.run("sample", np.zeros((n, Z), np.float32))
            engine.run("features", np.zeros((n, FEAT), np.float32))
        assert engine.compile_counts == before  # warmup covered the ladder
        assert all(c <= len(engine.buckets) for c in engine.compile_counts.values())

    def test_oversized_batch_chunks_through_top_bucket(self, engine):
        out = engine.run("classify", np.zeros((20, FEAT), np.float32))
        assert out.shape == (20, CLASSES)
        assert engine.compile_counts["classify"] <= len(engine.buckets)

    def test_features_returns_feature_vertex_activation(self, engine):
        out = engine.run("features", np.zeros((2, FEAT), np.float32))
        assert out.shape == (2, HIDDEN)

    def test_bad_inputs_rejected(self, engine):
        with pytest.raises(KeyError, match="unknown request kind"):
            engine.run("nope", np.zeros((1, FEAT), np.float32))
        with pytest.raises(ValueError, match="expected"):
            engine.run("classify", np.zeros((1, FEAT + 1), np.float32))
        with pytest.raises(ValueError, match="expected"):
            engine.run("classify", np.zeros((0, FEAT), np.float32))

    def test_generator_only_engine_has_no_classify(self, checkpoints):
        gen_path, _ = checkpoints
        eng = ServingEngine.from_checkpoints(generator=gen_path, buckets=(1,))
        assert eng.kinds == ("sample",)

    def test_unknown_feature_vertex_rejected(self, checkpoints):
        gen_path, cv_path = checkpoints
        with pytest.raises(ValueError, match="feature vertex"):
            ServingEngine.from_checkpoints(
                generator=gen_path, classifier=cv_path,
                buckets=(1,), feature_vertex="not_a_vertex",
            )


class TestFastPath:
    """The serve fast path: staged device assembly, dispatch/finalize,
    multi-replica routing, eager warmup. Bit-exactness is judged against
    ``run_host`` — the PR 3 host concat+pad reference kept in-tree as the
    oracle."""

    def test_staged_assembly_is_bit_identical_to_host_path(self, engine):
        """Every bucket, plus the chunked >top-bucket path: the staged
        buffer path must produce EXACTLY the host-concat result (same
        executables, same padded input bytes — not merely allclose)."""
        rng = np.random.default_rng(7)
        for n in (1, 2, 5, 8, 13, 20):  # rides 1-bucket, 8-bucket, chunks
            for kind, width in (("sample", Z), ("classify", FEAT),
                                ("features", FEAT)):
                rows = rng.random((n, width), dtype=np.float32)
                np.testing.assert_array_equal(
                    engine.run(kind, rows), engine.run_host(kind, rows),
                    err_msg=f"{kind} n={n}",
                )

    def test_staging_pool_reuse_cannot_leak_previous_rows(self, engine):
        """A big flush then a small one reuse the same staging buffer —
        the shrink tail must be re-zeroed or padding leaks old rows."""
        rng = np.random.default_rng(8)
        big = rng.random((8, FEAT), dtype=np.float32)
        small = rng.random((3, FEAT), dtype=np.float32)
        engine.run("classify", big)
        np.testing.assert_array_equal(
            engine.run("classify", small), engine.run_host("classify", small)
        )

    def test_dispatch_finalize_coalesces_riders(self, engine):
        """dispatch takes the riders as a LIST (no host concat in the
        batcher) and finalize hands back the concatenated rows."""
        rng = np.random.default_rng(9)
        a = rng.random((2, FEAT), dtype=np.float32)
        b = rng.random((3, FEAT), dtype=np.float32)
        out = engine.finalize(engine.dispatch("classify", [a, b]))
        np.testing.assert_array_equal(
            out, engine.run_host("classify", np.concatenate([a, b]))
        )

    def test_multi_replica_routing_and_parity(self, checkpoints):
        """replicas=2 on the suite's forced host devices: results stay
        bit-identical to the single-replica host path, dispatches spread
        across replicas, compiles stay ≤ ladder size per (kind, replica),
        and no compile happens at serve time after warmup."""
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 8), feature_vertex="feat_1", replicas=2,
        )
        assert eng.replica_count == 2
        eng.warmup()
        rng = np.random.default_rng(10)
        for i in range(8):
            rows = rng.random((1 + i % 6, FEAT), dtype=np.float32)
            np.testing.assert_array_equal(
                eng.run("classify", rows), eng.run_host("classify", rows)
            )
        stats = eng.stats()
        assert sum(stats["replica_dispatches"]) == 8
        assert all(d > 0 for d in stats["replica_dispatches"])  # both used
        # per-replica executables stay within the ladder (3 kinds × 2 buckets)
        assert all(c <= len(eng.buckets) * len(eng.kinds)
                   for c in stats["compiled_per_replica"])
        assert all(c <= eng.expected_max_compiles
                   for c in eng.compile_counts.values())
        assert all(c == 0 for c in eng.serve_compile_counts.values())

    def test_flight_lane_follows_the_routed_replica(self, checkpoints):
        """dispatch stamps the flight's completion lane with the replica
        it routed to — what the batcher's per-replica completer lanes key
        on. Least-loaded routing alternates two back-to-back dispatches
        across both replicas."""
        gen_path, _ = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, buckets=(1, 8), replicas=2,
        )
        eng.warmup()
        rows = np.zeros((1, Z), np.float32)
        f1 = eng.dispatch("sample", [rows])
        f2 = eng.dispatch("sample", [rows])
        assert {f1.lane, f2.lane} == {0, 1}
        for f in (f1, f2):
            assert f.lane == f.parts[0][3]  # the chunk's replica
            eng.finalize(f)

    def test_bulk_lane_splits_oversized_batches_across_replicas(
            self, checkpoints):
        """A single caller batch ≥ top_bucket × replicas rides one
        mesh-sharded executable — and still matches the host path bit for
        bit."""
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 4), feature_vertex="feat_1", replicas=2,
        )
        eng.warmup()
        before = eng.compile_counts
        rng = np.random.default_rng(11)
        rows = rng.random((10, FEAT), dtype=np.float32)  # 8-slab + 2 tail
        np.testing.assert_array_equal(
            eng.run("classify", rows), eng.run_host("classify", rows)
        )
        assert eng.compile_counts == before  # bulk lane was pre-compiled
        assert all(c == 0 for c in eng.serve_compile_counts.values())

    def test_eager_warmup_reports_warming_then_warm(self, checkpoints):
        """warmup(background=True): the engine serves immediately, flips
        ``warming`` off when the ladder is compiled, and every request
        thereafter is compile-free."""
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 8), feature_vertex="feat_1",
        )
        svc = InferenceService(eng, warmup="eager", max_latency=0.002)
        code, body = svc.handle("GET", "/healthz")
        assert code == 200 and body["status"] in ("warming", "ok")
        assert eng.wait_warm(60.0)
        code, body = svc.handle("GET", "/healthz")
        assert code == 200 and body["status"] == "ok"
        res = svc.classify(np.zeros((2, FEAT), np.float32))
        assert res.ok
        assert all(c == 0 for c in eng.serve_compile_counts.values())
        metrics = svc.metrics()
        assert metrics["engine"]["warmup"] == "warm"
        svc.close()

    def test_staging_high_water_shrinks_after_reset(self):
        from gan_deeplearning4j_tpu.serving.engine import _StagingBuf

        buf = _StagingBuf(8, 3)
        buf.arr[:8] = 1.0
        buf.reset_tail(8)
        assert buf.high_water == 8
        buf.arr[:2] = 2.0
        buf.reset_tail(2)
        # tail re-zeroed AND high-water shrank — a later reset_tail(3)
        # must not re-memset rows it knows are zero
        assert buf.high_water == 2
        np.testing.assert_array_equal(buf.arr[2:], 0.0)

    def test_failed_chunk_releases_all_replica_reservations(
            self, checkpoints):
        """A multi-chunk dispatch that dies on a later chunk must undo
        EVERY chunk's in-flight reservation, or least-loaded routing
        counts phantom load forever."""
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 4), feature_vertex="feat_1",
        )
        eng.warmup()
        real = eng._executable
        calls = {"n": 0}

        def flaky(kind, bucket, replica=0):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise RuntimeError("chunk 2 boom")
            return real(kind, bucket, replica)

        eng._executable = flaky
        try:
            with pytest.raises(RuntimeError, match="chunk 2 boom"):
                eng.dispatch("classify", [np.zeros((6, FEAT), np.float32)])
        finally:
            eng._executable = real
        assert eng.stats()["replica_in_flight"] == [0]

    def test_failed_background_warmup_surfaces_in_healthz(self, checkpoints):
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 8), feature_vertex="feat_1",
        )
        # poison one kind so the ladder cannot compile
        def boom(p, x):
            raise RuntimeError("trace boom")

        eng._kinds["classify"] = ("classifier", boom)
        t = eng.warmup(background=True)
        t.join(120.0)
        assert eng.warm_failed and not eng.warmed
        with pytest.raises(RuntimeError, match="warmup failed"):
            eng.wait_warm(1.0)
        svc = InferenceService(eng, warmup=False)
        code, body = svc.handle("GET", "/healthz")
        svc.close()
        assert code == 200 and body["status"] == "error"
        assert "warmup" in body["error"]
        assert eng.stats()["warmup"] == "failed"

    def test_replicas_beyond_devices_rejected(self, checkpoints):
        import jax

        gen_path, _ = checkpoints
        with pytest.raises(ValueError, match="replicas"):
            ServingEngine.from_checkpoints(
                generator=gen_path, buckets=(1,),
                replicas=len(jax.local_devices()) + 1,
            )


class _FakeAsyncEngine:
    """dispatch/finalize protocol fake: sleeps model the two stages and a
    counter proves (a) the stages actually overlapped and (b) the
    in-flight window bound was honored."""

    def __init__(self, dispatch_s=0.0, finalize_s=0.0, replica_count=1):
        self.dispatch_s = dispatch_s
        self.finalize_s = finalize_s
        self.replica_count = replica_count
        self.lock = threading.Lock()
        self.in_flight = 0
        self.max_in_flight = 0
        self.dispatches = 0

    def dispatch(self, kind, rows_list):
        with self.lock:
            self.in_flight += 1
            self.dispatches += 1
            self.max_in_flight = max(self.max_in_flight, self.in_flight)
        time.sleep(self.dispatch_s)
        return (kind, [np.asarray(r) for r in rows_list])

    def finalize(self, handle):
        time.sleep(self.finalize_s)
        with self.lock:
            self.in_flight -= 1
        kind, rows_list = handle
        rows = rows_list[0] if len(rows_list) == 1 else np.concatenate(rows_list)
        return rows * 2.0


class TestPipelining:
    """The two-stage dispatch/completion pipeline against a fake slow
    engine: overlap is real (wall clock beats the serial sum of stage
    times) and the in-flight window is a hard bound."""

    FLUSHES, STAGE_S = 8, 0.05

    def _drive(self, depth):
        eng = _FakeAsyncEngine(dispatch_s=self.STAGE_S,
                               finalize_s=self.STAGE_S)
        mb = MicroBatcher(engine=eng, max_batch=8, max_latency=0.0,
                          max_queue=64, pipeline_depth=depth)
        results = [None] * self.FLUSHES

        def client(i):
            # distinct kinds -> no coalescing -> exactly FLUSHES flushes
            results[i] = mb.submit(f"k{i}", np.full((1, 3), float(i),
                                                    np.float32), timeout=30.0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(self.FLUSHES)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        mb.close()
        assert all(r.ok for r in results)
        for i, r in enumerate(results):
            np.testing.assert_array_equal(r.data, np.full((1, 3), 2.0 * i))
        return eng, mb, wall

    def test_pipeline_overlaps_assembly_with_device_execution(self):
        # self-calibrating: measure the strictly-serial depth-1 wall under
        # the SAME machine conditions, then require depth 2 to beat it by
        # a margin only stage overlap can explain (ideal ratio ≈ 0.56 for
        # equal stage sleeps; 0.8 leaves room for scheduling noise)
        _, _, serial_wall = self._drive(depth=1)
        eng, mb, wall = self._drive(depth=2)
        assert wall < 0.8 * serial_wall, (
            f"no overlap: wall={wall:.3f}s vs serial={serial_wall:.3f}s")
        assert eng.max_in_flight == 2  # overlap happened AND was bounded
        m = mb.metrics()
        assert m["pipeline"]["depth"] == 2
        assert set(m["pipeline"]["stage_ms"]) == {"assemble", "device",
                                                  "complete"}

    def test_depth_one_is_strictly_serial(self):
        eng, mb, wall = self._drive(depth=1)
        assert eng.max_in_flight == 1  # the bound held everywhere
        assert wall >= self.FLUSHES * 2 * self.STAGE_S * 0.9

    def test_dispatch_error_errors_its_riders_only(self):
        class BadDispatch(_FakeAsyncEngine):
            def dispatch(self, kind, rows_list):
                if kind == "bad":
                    raise RuntimeError("dispatch boom")
                return super().dispatch(kind, rows_list)

        mb = MicroBatcher(engine=BadDispatch(), max_latency=0.0)
        bad = mb.submit("bad", np.zeros((1, 2), np.float32), timeout=5.0)
        good = mb.submit("good", np.ones((1, 2), np.float32), timeout=5.0)
        mb.close()
        assert bad.status == "error" and "dispatch boom" in bad.error
        assert good.ok
        assert mb.metrics()["errors"] == 1

    def test_sparse_kind_is_not_starved_by_full_batches(self):
        """Sustained full batches of one kind must not hold a sparse
        kind's partial forever: once the sparse request burns half its
        deadline budget queued, its kind cuts regardless."""
        eng = _FakeAsyncEngine(finalize_s=0.02)
        mb = MicroBatcher(engine=eng, max_batch=4, max_latency=0.01,
                          max_queue=64, pipeline_depth=1)
        stop = threading.Event()

        def producer():
            while not stop.is_set():
                mb.submit("a", np.ones((4, 2), np.float32), timeout=5.0)

        producers = [threading.Thread(target=producer) for _ in range(3)]
        for t in producers:
            t.start()
        time.sleep(0.1)  # the 'a' stream is saturating the device
        res = mb.submit("b", np.ones((1, 2), np.float32), timeout=2.0)
        stop.set()
        for t in producers:
            t.join(10.0)
        mb.close()
        assert res.ok, (res.status, res.error)
        assert res.latency_s < 1.9  # served via the fairness bound

    def test_oversized_rider_is_not_starved_by_fitting_riders(self):
        """A rider above max_batch must cut alone (the engine chunks it),
        not be leapfrogged forever by younger fitting same-kind riders."""
        eng = _FakeAsyncEngine(finalize_s=0.01)
        mb = MicroBatcher(engine=eng, max_batch=4, max_latency=0.005,
                          max_queue=64, pipeline_depth=1)
        stop = threading.Event()

        def producer():
            while not stop.is_set():
                mb.submit("k", np.ones((4, 2), np.float32), timeout=5.0)

        producers = [threading.Thread(target=producer) for _ in range(2)]
        for t in producers:
            t.start()
        time.sleep(0.05)
        big = mb.submit("k", np.ones((9, 2), np.float32), timeout=3.0)
        stop.set()
        for t in producers:
            t.join(10.0)
        mb.close()
        assert big.ok, (big.status, big.error)
        np.testing.assert_array_equal(big.data, np.full((9, 2), 2.0))

    def test_finalize_error_errors_its_riders_only(self):
        class BadFinalize(_FakeAsyncEngine):
            def finalize(self, handle):
                if handle[0] == "bad":
                    raise RuntimeError("finalize boom")
                return super().finalize(handle)

        mb = MicroBatcher(engine=BadFinalize(), max_latency=0.0)
        bad = mb.submit("bad", np.zeros((1, 2), np.float32), timeout=5.0)
        good = mb.submit("good", np.ones((1, 2), np.float32), timeout=5.0)
        mb.close()
        assert bad.status == "error" and "finalize boom" in bad.error
        assert good.ok
        total = mb.metrics()
        assert sum(total["completed"].values()) + total["errors"] == 2


class _LaneHandle:
    """Flight handle with the ``lane`` attribute a multi-replica engine's
    dispatch stamps (the batcher keys its completion lanes on it)."""

    def __init__(self, kind, rows_list, lane):
        self.kind = kind
        self.rows_list = rows_list
        self.lane = lane


class _TwoReplicaEngine:
    """Fake two-replica engine: kind 'slow' routes to replica 0 (long
    finalize), everything else to replica 1 (fast finalize)."""

    replica_count = 2
    default_pipeline_depth = 4

    def __init__(self, slow_s=0.4, fast_s=0.01):
        self.finalize_s = {0: slow_s, 1: fast_s}

    def dispatch(self, kind, rows_list):
        return _LaneHandle(kind, [np.asarray(r) for r in rows_list],
                           0 if kind == "slow" else 1)

    def finalize(self, handle):
        time.sleep(self.finalize_s[handle.lane])
        rows = (handle.rows_list[0] if len(handle.rows_list) == 1
                else np.concatenate(handle.rows_list))
        return rows * 2.0


class TestCompletionLanes:
    """Per-replica completion lanes: one replica's slow finalize must not
    head-of-line block another replica's already-finished flush (the PR 4
    re-queued remainder)."""

    def test_cross_replica_completion_overlap(self):
        eng = _TwoReplicaEngine(slow_s=0.4, fast_s=0.01)
        mb = MicroBatcher(engine=eng, max_latency=0.0, max_queue=64)
        assert mb.metrics()["pipeline"]["lanes"] == 2
        done = {}

        def client(kind):
            r = mb.submit(kind, np.ones((1, 3), np.float32), timeout=10.0)
            done[kind] = (time.monotonic(), r)

        t_slow = threading.Thread(target=client, args=("slow",))
        t_fast = threading.Thread(target=client, args=("fast",))
        t0 = time.monotonic()
        t_slow.start()
        time.sleep(0.05)  # the slow flush is dispatched (and finalizing)
        t_fast.start()
        t_fast.join(timeout=10.0)
        t_slow.join(timeout=10.0)
        mb.close()
        assert done["fast"][1].ok and done["slow"][1].ok
        # the fast lane completed while the slow finalize was still
        # running: with the old single global completer the fast flush
        # would have queued behind the 0.4s finalize ahead of it
        assert done["fast"][0] < done["slow"][0]
        assert done["fast"][0] - t0 < 0.3, (
            "fast replica's completion was head-of-line blocked by the "
            "slow replica's finalize")

    def test_laneless_handles_ride_lane_zero(self):
        # run_fn handles and single-replica fakes carry no lane: the
        # batcher must fold them onto lane 0, reproducing the old
        # single-completer behavior exactly
        eng = _FakeAsyncEngine()
        mb = MicroBatcher(engine=eng, max_latency=0.0)
        assert mb.metrics()["pipeline"]["lanes"] == 1
        r = mb.submit("k", np.ones((1, 2), np.float32), timeout=5.0)
        mb.close()
        assert r.ok

    def test_lane_wider_than_batcher_folds_modulo(self):
        # a swap to an engine with MORE replicas than the batcher has
        # lanes must still finalize every flight (modulo folding)
        class WideEngine(_TwoReplicaEngine):
            def dispatch(self, kind, rows_list):
                h = super().dispatch(kind, rows_list)
                h.lane = 5  # beyond the 2 lanes the batcher built
                return h

        eng = WideEngine(slow_s=0.0, fast_s=0.0)
        eng.finalize_s = {i: 0.0 for i in range(8)}
        mb = MicroBatcher(engine=eng, max_latency=0.0)
        r = mb.submit("k", np.ones((1, 2), np.float32), timeout=5.0)
        mb.close()
        assert r.ok


class TestBatcher:
    """Policy tests against a fake engine — no jax, pure threading."""

    def test_coalesces_concurrent_requests(self):
        batches = []

        def run_fn(kind, rows):
            batches.append((kind, rows.shape[0]))
            time.sleep(0.01)
            return rows * 2.0

        mb = MicroBatcher(run_fn, max_batch=16, max_latency=0.05, max_queue=64)
        results = [None] * 8

        def client(i):
            results[i] = mb.submit("k", np.full((2, 3), float(i), np.float32))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.close()
        assert all(r.ok for r in results)
        for i, r in enumerate(results):  # each caller gets ITS rows back
            np.testing.assert_array_equal(r.data, np.full((2, 3), 2.0 * i))
        # coalescing happened: fewer flushes than requests
        assert len(batches) < 8
        m = mb.metrics()
        assert m["submitted"] == {"k": 8} and m["completed"] == {"k": 8}
        assert sum(m["batch_occupancy"].values()) == m["flushes"]

    def test_backpressure_sheds_immediately_when_full(self):
        """The acceptance criterion: with a full queue, a new request is
        shed within its deadline instead of blocking indefinitely."""
        release, running = threading.Event(), threading.Event()

        def slow_fn(kind, rows):
            running.set()
            release.wait(5.0)
            return rows

        mb = MicroBatcher(slow_fn, max_batch=4, max_latency=0.0, max_queue=1,
                          default_timeout=10.0)
        first = {}
        t = threading.Thread(
            target=lambda: first.setdefault(
                "r", mb.submit("k", np.zeros((1, 2), np.float32))
            )
        )
        t.start()
        assert running.wait(5.0)  # worker is inside the engine, queue empty
        # fill the queue with one waiter…
        t2 = threading.Thread(
            target=lambda: first.setdefault(
                "r2", mb.submit("k", np.zeros((1, 2), np.float32))
            )
        )
        t2.start()
        deadline = time.monotonic() + 2.0
        while mb.metrics()["queue_depth"] < 1:
            assert time.monotonic() < deadline, "second request never queued"
            time.sleep(0.001)
        # …then the overflow request must shed NOW, not after 10 s
        t0 = time.monotonic()
        shed = mb.submit("k", np.zeros((1, 2), np.float32), timeout=10.0)
        elapsed = time.monotonic() - t0
        assert shed.status == "overloaded"
        assert elapsed < 1.0  # immediate, not deadline-bound
        release.set()
        t.join(5.0)
        t2.join(5.0)
        mb.close()
        assert first["r"].ok and first["r2"].ok
        assert mb.metrics()["shed_overloaded"] == 1

    def test_deadline_expiry_sheds_before_device_work(self):
        ran = []

        def slow_fn(kind, rows):
            ran.append(rows.shape[0])
            time.sleep(0.2)
            return rows

        mb = MicroBatcher(slow_fn, max_batch=4, max_latency=0.0, max_queue=8)
        hold = threading.Thread(
            target=lambda: mb.submit("k", np.zeros((1, 2), np.float32))
        )
        hold.start()
        while not ran:
            time.sleep(0.001)
        # queued behind a 200 ms flush with a 50 ms budget: must expire
        res = mb.submit("k", np.zeros((3, 2), np.float32), timeout=0.05)
        assert res.status == "deadline"
        hold.join(5.0)
        mb.close()
        assert mb.metrics()["shed_deadline"] == 1
        assert ran == [1]  # the expired request never reached the engine

    def test_engine_error_propagates_as_error_result(self):
        def bad_fn(kind, rows):
            raise RuntimeError("boom")

        mb = MicroBatcher(bad_fn, max_latency=0.0)
        res = mb.submit("k", np.zeros((1, 2), np.float32), timeout=1.0)
        mb.close()
        assert res.status == "error" and "boom" in res.error
        assert mb.metrics()["errors"] == 1

    def test_malformed_rows_rejected_client_side(self):
        mb = MicroBatcher(lambda k, r: r)
        res = mb.submit("k", np.zeros((3,), np.float32))
        mb.close()
        assert res.status == "error" and "expected" in res.error

    def test_width_mismatched_rider_cannot_kill_the_worker(self):
        """A bad request coalesced with a good one must error its batch,
        not crash the worker thread and wedge the service."""
        mb = MicroBatcher(lambda k, r: r, max_batch=8, max_latency=0.05)
        results = {}

        def client(name, width):
            results[name] = mb.submit("k", np.zeros((1, width), np.float32),
                                      timeout=5.0)

        threads = [
            threading.Thread(target=client, args=("a", 2)),
            threading.Thread(target=client, args=("b", 3)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # whatever happened to the mixed batch, the worker must survive
        # and serve the next request
        after = mb.submit("k", np.ones((2, 2), np.float32), timeout=5.0)
        mb.close()
        assert after.ok
        assert all(r.status in ("ok", "error") for r in results.values())

    def test_close_without_drain_keeps_the_ledger(self):
        release, running = threading.Event(), threading.Event()

        def slow_fn(kind, rows):
            running.set()
            release.wait(5.0)
            return rows

        mb = MicroBatcher(slow_fn, max_latency=0.0, max_queue=8)
        done = {}
        t1 = threading.Thread(target=lambda: done.setdefault(
            "a", mb.submit("k", np.zeros((1, 2), np.float32))))
        t1.start()
        assert running.wait(5.0)
        t2 = threading.Thread(target=lambda: done.setdefault(
            "b", mb.submit("k", np.zeros((1, 2), np.float32))))
        t2.start()
        deadline = time.monotonic() + 2.0
        while mb.metrics()["queue_depth"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.001)
        # close() joins the worker, which is blocked inside the engine —
        # release it a beat later so close returns promptly
        threading.Timer(0.2, release.set).start()
        mb.close(drain=False)  # sheds the queued request, counted
        t1.join(5.0)
        t2.join(5.0)
        m = mb.metrics()
        total = (sum(m["completed"].values()) + m["shed_overloaded"]
                 + m["shed_deadline"] + m["errors"])
        assert sum(m["submitted"].values()) == total == 2


class TestServiceSmoke:
    """The tier-1 fast smoke: in-process service, 2 buckets, ~50 mixed
    requests from concurrent clients — every request accounted for."""

    def test_fifty_mixed_requests_zero_lost(self, engine):
        svc = InferenceService(engine, max_latency=0.002, max_queue=64,
                               default_timeout=30.0, warmup=True)
        width = {"sample": Z, "classify": FEAT, "features": FEAT}
        statuses = []
        lock = threading.Lock()

        def client(widx):
            rng = np.random.default_rng(widx)
            for _ in range(10):
                kind = engine.kinds[rng.integers(len(engine.kinds))]
                n = int(rng.integers(1, 9))
                res = svc.batcher.submit(
                    kind, rng.random((n, width[kind]), dtype=np.float32)
                )
                with lock:
                    statuses.append((kind, n, res))

        threads = [threading.Thread(target=client, args=(w,)) for w in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        metrics = svc.metrics()
        svc.close()
        assert len(statuses) == 50  # zero lost: one result per submit
        for kind, n, res in statuses:
            assert res.ok, (kind, res.status, res.error)
            assert res.data.shape[0] == n
        # metrics schema: the /metrics contract docs/SERVING.md pins
        assert sum(metrics["completed"].values()) == 50
        for kind in engine.kinds:
            lat = metrics["latency_ms"].get(kind)
            if lat:
                assert {"p50", "p95", "p99"} <= set(lat)
                assert lat["p50"] <= lat["p95"] <= lat["p99"]
        assert metrics["compile_counts"] == engine.compile_counts
        assert all(
            c <= len(engine.buckets) for c in metrics["compile_counts"].values()
        )

    def test_eager_warmup_two_replicas_twenty_mixed_requests(
            self, checkpoints):
        """The CI fast-path smoke: engine on 2 (forced host) devices,
        eager background warmup, 20 mixed-kind requests round-tripped —
        zero lost, no serve-time compiles, both replicas routed."""
        gen_path, cv_path = checkpoints
        eng = ServingEngine.from_checkpoints(
            generator=gen_path, classifier=cv_path,
            buckets=(1, 8), feature_vertex="feat_1", replicas=2,
        )
        svc = InferenceService(eng, warmup="eager", max_latency=0.002,
                               default_timeout=30.0)
        assert eng.wait_warm(120.0)
        width = {"sample": Z, "classify": FEAT, "features": FEAT}
        statuses = []
        lock = threading.Lock()

        def client(widx):
            rng = np.random.default_rng(100 + widx)
            for _ in range(5):
                kind = eng.kinds[rng.integers(len(eng.kinds))]
                n = int(rng.integers(1, 9))
                res = svc.batcher.submit(
                    kind, rng.random((n, width[kind]), dtype=np.float32)
                )
                with lock:
                    statuses.append(res)

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = eng.stats()
        svc.close()
        assert len(statuses) == 20  # zero lost
        assert all(r.ok for r in statuses), [
            (r.status, r.error) for r in statuses if not r.ok]
        assert all(c == 0 for c in eng.serve_compile_counts.values())
        assert sum(stats["replica_dispatches"]) >= 1
        assert all(c <= len(eng.buckets) * len(eng.kinds)
                   for c in stats["compiled_per_replica"])

    def test_healthz_and_routing(self, engine):
        svc = InferenceService(engine, warmup=False)
        code, body = svc.handle("GET", "/healthz")
        assert code == 200 and body["status"] == "ok"
        assert set(body["kinds"]) == set(engine.kinds)
        code, body = svc.handle("POST", "/v1/classify", {"data": [[0.1] * FEAT]})
        assert code == 200 and body["status"] == "ok"
        assert len(body["data"]) == 1 and len(body["data"][0]) == CLASSES
        code, body = svc.handle("POST", "/v1/nope", {"data": [[1.0]]})
        assert code == 404
        code, body = svc.handle("POST", "/v1/classify", {})
        assert code == 400
        code, body = svc.handle("POST", "/v1/classify", {"data": "junk"})
        assert code == 400
        # malformed shapes 400 at the boundary — they never reach a batch
        code, body = svc.handle("POST", "/v1/classify", {"data": [[]]})
        assert code == 400 and "expected" in body["error"]
        code, body = svc.handle("POST", "/v1/classify",
                                {"data": [[0.1] * (FEAT + 1)]})
        assert code == 400
        # non-numeric timeout is a 400, not a handler-thread crash
        code, body = svc.handle("POST", "/v1/classify",
                                {"data": [[0.1] * FEAT], "timeout": "abc"})
        assert code == 400 and "timeout" in body["error"]
        code, body = svc.handle("POST", "/v1/classify",
                                {"data": [[0.1] * FEAT], "timeout": "5"})
        assert code == 200  # numeric strings coerce
        svc.close()


class TestDrainState:
    """POST /admin/drain — the fleet manager's draining-restart handshake
    (docs/FLEET.md): the worker leaves the admittable /healthz set but
    keeps answering until its pipeline empties."""

    def test_drain_marks_clears_and_keeps_serving(self, engine):
        svc = InferenceService(engine, warmup=False)
        try:
            assert svc.healthz()["status"] == "ok"
            code, body = svc.handle("POST", "/admin/drain")
            assert code == 200 and body["draining"] is True
            assert svc.healthz()["status"] == "draining"
            assert svc.metrics()["draining"] is True
            # draining is advisory: in-flight and late requests still
            # answer normally (the router stopped routing, not the worker)
            assert svc.sample(np.zeros((2, Z), np.float32)).ok
            code, body = svc.handle("POST", "/admin/drain?off=1")
            assert code == 200 and body["draining"] is False
            assert svc.healthz()["status"] == "ok"
            assert svc.metrics()["draining"] is False
        finally:
            svc.close()


class TestHttpServer:
    def test_http_round_trip(self, engine):
        svc = InferenceService(engine, warmup=False)
        server = make_server(svc, port=0)
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            base = f"http://127.0.0.1:{port}"
            with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"
            req = urllib.request.Request(
                f"{base}/v1/sample",
                data=json.dumps({"data": [[0.0] * Z, [0.5] * Z]}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                body = json.loads(r.read())
            assert body["status"] == "ok" and len(body["data"]) == 2
            with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
                metrics = json.loads(r.read())
            assert metrics["completed"].get("sample") == 1
        finally:
            server.shutdown()
            server.server_close()
            svc.close()


class TestPublishRoundTrip:
    def test_publish_for_serving_then_load_bundle(self, tmp_path):
        """The deploy path end to end: experiment → bundle → engine, no
        training code on the load side."""
        from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

        cfg = ExperimentConfig(
            batch_size_train=8, num_iterations=1, latent_grid=2,
            output_dir=str(tmp_path / "out"), save_models=False,
        )
        exp = GanExperiment(cfg)
        manifest = exp.publish_for_serving(str(tmp_path / "bundle"))
        assert manifest["classifier"] is not None
        assert manifest["feature_vertex"] == "dis_dense_layer_6"
        bundle_dir = manifest["directory"]
        assert os.path.exists(os.path.join(bundle_dir, "serving.json"))
        assert not [f for f in os.listdir(bundle_dir) if f.endswith(".tmp")]

        eng = ServingEngine.from_bundle(bundle_dir, buckets=(4,))
        assert set(eng.kinds) == {"sample", "classify", "features"}
        probs = eng.run(
            "classify", np.zeros((3, manifest["num_features"]), np.float32)
        )
        assert probs.shape == (3, manifest["num_classes"])
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)
        z = np.zeros((2, manifest["z_size"]), np.float32)
        assert eng.run("sample", z).shape == (2, manifest["num_features"])

    def test_bundle_checkpoints_have_no_updater_state(self, tmp_path):
        from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
        from gan_deeplearning4j_tpu.utils import read_model

        cfg = ExperimentConfig(
            batch_size_train=8, num_iterations=1,
            output_dir=str(tmp_path / "out"), save_models=False,
        )
        manifest = GanExperiment(cfg).publish_for_serving(str(tmp_path / "b"))
        for key in ("generator", "classifier"):
            _, _, opt_state, _ = read_model(
                os.path.join(manifest["directory"], manifest[key])
            )
            assert opt_state is None


@pytest.mark.slow
class TestServeBench:
    def test_bench_script_invariants(self, tmp_path):
        """serve_bench on CPU: mixed sizes complete with zero lost requests,
        bounded compiles, and a BENCH JSON artifact on disk."""
        out = str(tmp_path / "serve_bench.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
             "--requests", "48", "--threads", "4", "--buckets", "1,8",
             "--sizes", "1,3,8", "--output", out],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(out) as fh:
            summary = json.load(fh)
        res = summary["results"]
        assert summary["invariants"]["zero_lost"]
        assert summary["invariants"]["compiles_bounded"]
        assert summary["invariants"]["no_serve_time_compiles"]
        assert summary["invariants"]["overload_zero_lost"]
        assert res["lost"] == 0 and res["errors"] == 0
        assert res["ok"] + res["shed"] == summary["config"]["requests"]
        assert res["throughput_rps"] > 0
        for kind, counts in res["compile_counts"].items():
            assert counts <= 2, (kind, counts)
        for kind, counts in res["serve_compile_counts"].items():
            assert counts == 0, (kind, counts)
        for lat in res["latency_ms"].values():
            assert {"p50", "p95", "p99"} <= set(lat)
        # the overload phase must have actually exercised shedding
        assert summary["overload"]["returned"] == summary["overload"]["requests"]
        # per-stage pipeline breakdown present for the fast path
        assert {"assemble", "device", "complete"} <= set(
            res["pipeline"]["stage_ms"])
