"""scripts/bench_ledger.py — the BENCH trajectory trend table + gate.

The ROADMAP's "TPU-measured truth" machine gate: rounds fold into one
table, the newest round gates against a baseline round under per-metric
tolerances, and hard bounds (lost > 0) fail regardless of history.
Stdlib-only, driven through the CLI the campaign post-step uses.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(root, name, doc):
    with open(os.path.join(root, name), "w") as fh:
        json.dump(doc, fh)


def _run(root, *argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_ledger.py"),
         "--root", str(root), *argv],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )


def _fleet_record(ok=5000, lost=0, invariants_ok=True):
    return {"bench": "fleet_drill", "ok": invariants_ok,
            "results": {"requests": {"ok": ok, "lost": lost}},
            "invariants": {"exactly_one_answer_zero_lost": invariants_ok}}


class TestBenchLedger:
    def test_trend_table_with_delta_vs_baseline(self, tmp_path):
        _write(tmp_path, "BENCH_serving_r01.json", {
            "ok": True, "results": {"throughput_rps": 40.0, "lost": 0}})
        _write(tmp_path, "BENCH_serving_r02.json", {
            "ok": True, "results": {"throughput_rps": 44.0, "lost": 0}})
        proc = _run(tmp_path, "--json", str(tmp_path / "ledger.json"))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        ledger = json.load(open(tmp_path / "ledger.json"))
        rows = ledger["families"]["serving"]["rounds"]
        assert ledger["families"]["serving"]["baseline"] == "r01"
        delta = rows[1]["metrics"]["throughput_rps"]["delta_vs_r01"]
        assert abs(delta - 0.10) < 1e-9
        assert "no regressions" in proc.stdout

    def test_regression_past_tolerance_fails(self, tmp_path):
        # throughput tolerance is -30%: a 50% drop must gate
        _write(tmp_path, "BENCH_serving_r01.json", {
            "ok": True, "results": {"throughput_rps": 40.0, "lost": 0}})
        _write(tmp_path, "BENCH_serving_r02.json", {
            "ok": True, "results": {"throughput_rps": 20.0, "lost": 0}})
        proc = _run(tmp_path)
        assert proc.returncode == 1
        assert "REGRESSIONS" in proc.stdout
        assert "throughput_rps" in proc.stdout

    def test_hard_bound_breach_fails_without_history(self, tmp_path):
        # a single round with lost > 0 gates on its own
        _write(tmp_path, "BENCH_fleet_r01.json", _fleet_record(lost=3))
        proc = _run(tmp_path)
        assert proc.returncode == 1
        assert "hard bound" in proc.stdout

    def test_failed_verdict_on_newest_round_fails(self, tmp_path):
        _write(tmp_path, "BENCH_fleet_r01.json", _fleet_record())
        _write(tmp_path, "BENCH_fleet_r02.json",
               _fleet_record(invariants_ok=False))
        proc = _run(tmp_path)
        assert proc.returncode == 1
        assert "failed verdict" in proc.stdout

    def test_baseline_round_pin(self, tmp_path):
        for rnd, rps in (("r01", 10.0), ("r02", 40.0), ("r03", 39.0)):
            _write(tmp_path, f"BENCH_serving_{rnd}.json", {
                "ok": True, "results": {"throughput_rps": rps, "lost": 0}})
        # vs r01 the latest looks like a 3.9x win; vs r02 it is -2.5%
        proc = _run(tmp_path, "--baseline", "r02")
        assert proc.returncode == 0
        assert "vs r02" in proc.stdout

    def test_unreadable_and_unnamed_files_tolerated(self, tmp_path):
        _write(tmp_path, "BENCH_fleet_r01.json", _fleet_record())
        with open(os.path.join(tmp_path, "BENCH_fleet_r02.json"), "w") as fh:
            fh.write("{broken")
        _write(tmp_path, "BENCH_BASELINES.json", {"not": "a record"})
        proc = _run(tmp_path)
        # the broken newest round has no verdict and no metrics — it
        # surfaces in the table, the gate reads what exists
        assert "unreadable" in proc.stderr
        assert "fleet" in proc.stdout

    def test_no_records_is_an_error(self, tmp_path):
        proc = _run(tmp_path)
        assert proc.returncode == 1
        assert "no BENCH_" in proc.stderr

    def test_gates_green_on_the_repo_itself(self):
        # the committed BENCH set must pass its own gate — the campaign
        # post-step runs exactly this
        proc = _run(REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
