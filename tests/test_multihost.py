"""Multi-host coordination smoke (round-2 VERDICT missing #4 / next #5).

Spawns TWO real OS processes that meet at a jax.distributed coordinator and
form one global mesh — the cross-process analog of the reference's
multi-JVM Spark architecture (dl4jGANComputerVision.java:317-330). Each
process runs one pmean step and one parameter-averaging round on
process-locally-fed global batches and prints a params checksum; this test
asserts the processes END UP BIT-IDENTICAL (same checksums), i.e. the
collectives really synchronized state across process boundaries.

The spawn/drain/validate plumbing lives in ``__graft_entry__.spawn_multihost``
(shared with ``dryrun_multihost`` so the two cannot drift).

Marked slow: two cold jax processes cost ~30-60 s.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from __graft_entry__ import spawn_multihost  # noqa: E402


@pytest.mark.slow
def test_two_process_distributed_training_agrees():
    checksums = spawn_multihost(2)
    assert len(checksums) == 2
    assert checksums[0] == checksums[1], f"cross-process divergence: {checksums}"
