"""Multi-host coordination smoke (round-2 VERDICT missing #4; round-4
VERDICT item 8 scales it to 4 processes and adds the WGAN-GP mode).

Spawns N real OS processes that meet at a jax.distributed coordinator and
form one global mesh — the cross-process analog of the reference's
multi-JVM Spark architecture (dl4jGANComputerVision.java:317-330). Each
process runs one pmean step, one parameter-averaging round, and one WGAN-GP
round on process-locally-fed global batches and prints a params checksum per
mode; this test asserts the processes END UP BIT-IDENTICAL (same checksums),
i.e. the collectives really synchronized state across process boundaries.

The spawn/drain/validate plumbing lives in ``__graft_entry__.spawn_multihost``
(shared with ``dryrun_multihost`` so the two cannot drift).

Marked slow: N cold jax processes cost ~30-90 s.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from __graft_entry__ import spawn_multihost  # noqa: E402


@pytest.mark.slow
@pytest.mark.parametrize("n_processes", [2, 4])
def test_distributed_training_agrees_across_processes(n_processes):
    checksums = spawn_multihost(n_processes)
    assert len(checksums) == n_processes
    assert all(len(c) == 3 for c in checksums)  # pmean, param_averaging, wgan
    assert all(
        c == checksums[0] for c in checksums[1:]
    ), f"cross-process divergence: {checksums}"
