"""The scan-of-K device training loop (round-3 perf work).

``train_iterations`` runs K full alternating iterations in one XLA dispatch
(lax.scan of the fused body). These tests pin its defining property — the
math is IDENTICAL to K sequential ``train_iteration`` calls (same weight
updates, same per-step RNG derived from the carried step counter, same loss
sequence) — and that ``run()``'s automatic windowing preserves observable
behavior (history, export artifacts) exactly.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gan_deeplearning4j_tpu.data import ArrayDataSetIterator, DeviceResidentIterator
from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

B, K = 8, 4


def _cfg(**kw) -> ExperimentConfig:
    base = dict(
        batch_size_train=B, batch_size_pred=B, num_iterations=10 ** 9,
        save_models=False,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _data(n_batches: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    feats = rng.random((n_batches, B, 784), dtype=np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (n_batches, B))]
    return feats, labels


class TestTrainIterations:
    @pytest.mark.slow
    def test_matches_sequential_iterations(self):
        feats, labels = _data(K)
        seq = GanExperiment(_cfg())
        seq_losses = [seq.train_iteration(feats[i], labels[i]) for i in range(K)]
        seq_d = [float(l["d_loss"]) for l in seq_losses]
        seq_c = [float(l["cv_loss"]) for l in seq_losses]

        scan = GanExperiment(_cfg())
        out = scan.train_iterations(feats, labels)
        np.testing.assert_allclose(np.asarray(out["d_loss"]), seq_d, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out["cv_loss"]), seq_c, rtol=2e-5, atol=1e-6)
        # end states agree too (same updates in the same order)
        for name in ("dis_state", "gan_state", "cv_state"):
            a = jax.tree_util.tree_leaves(getattr(seq, name).params)
            b = jax.tree_util.tree_leaves(getattr(scan, name).params)
            for x, y in zip(a, b):
                # scan vs straight-line compile to different fusion orders;
                # the near-sign-SGD RmsProp (decay 1e-8) amplifies the f32
                # reassociation residue chaotically over K steps, so end
                # params agree only to ~1e-3 absolute. A genuinely wrong
                # update (one mis-sequenced step) shifts params by ~K·lr ≈
                # 2e-2 — the loss-sequence check above plus this separator
                # still catches it.
                np.testing.assert_allclose(
                    np.asarray(x), np.asarray(y), rtol=0, atol=2e-3
                )
        assert int(scan.dis_state.step) == int(seq.dis_state.step) == 2 * K

    def test_resample_label_noise_runs_in_device_loop(self):
        # Round 5: the G/D-balance lever no longer forces per-dispatch
        # stepping — the scanned body redraws ε from the per-step key stream.
        feats, labels = _data(2)
        exp = GanExperiment(_cfg(resample_label_noise=True))
        out = exp.train_iterations(feats, labels)
        assert out["d_loss"].shape == (2,)
        # and the scan matches sequential fused calls bit-for-bit in loss
        # order (same body, same key stream)
        seq = GanExperiment(_cfg(resample_label_noise=True))
        seq_d = [float(seq.train_iteration(feats[i], labels[i])["d_loss"])
                 for i in range(2)]
        np.testing.assert_allclose(
            np.asarray(out["d_loss"]), seq_d, rtol=2e-5, atol=1e-6
        )

    def test_resampled_noise_differs_per_iteration(self):
        # With the quirk disabled, two iterations on IDENTICAL data must see
        # different softened labels — observable as different d_losses even
        # when dropout/z are the only other variation... so compare against
        # the quirk path where the same check uses identical noise: the
        # resampled run's dis updates diverge from the once-sampled run's
        # from iteration 1 onward.
        feats, labels = _data(1)
        feats = np.broadcast_to(feats, (2,) + feats.shape[1:]).copy()
        labels = np.broadcast_to(labels, (2,) + labels.shape[1:]).copy()
        quirk = GanExperiment(_cfg(seed=1))
        fresh = GanExperiment(_cfg(seed=1, resample_label_noise=True))
        dq = np.asarray(quirk.train_iterations(feats, labels)["d_loss"])
        df = np.asarray(fresh.train_iterations(feats, labels)["d_loss"])
        assert not np.allclose(dq, df)

    def test_dis_lr_decay_freezes_dis_at_rate_epsilon(self):
        # rate ≈ 0 with every=1: iteration 0 runs at scale 1 (γ^0), every
        # later iteration's dis update is scaled to ~nothing — dis params
        # stop moving while gen keeps training. Pins both the schedule
        # boundary (floor(iter/every)) and that the scale reaches ONLY dis.
        feats, labels = _data(3)
        exp = GanExperiment(_cfg(
            dis_lr_decay_every=1, dis_lr_decay_rate=1e-30,
        ))

        def trainable_dis(params):
            # BN running stats (role "state") update through the training
            # forward pass regardless of LR — compare optimizer-owned
            # leaves only
            opt = exp.dis_trainer.optimizer
            return {
                layer: {p: np.asarray(v).copy()
                        for p, v in lparams.items() if opt.trainable(layer, p)}
                for layer, lparams in params.items()
            }

        exp.train_iteration(feats[0], labels[0])
        dis_after_1 = trainable_dis(exp.dis_state.params)
        gen_after_1 = jax.tree_util.tree_map(
            lambda x: np.asarray(x).copy(), exp.gen_params
        )
        exp.train_iteration(feats[1], labels[1])
        for a, b in zip(jax.tree_util.tree_leaves(dis_after_1),
                        jax.tree_util.tree_leaves(
                            trainable_dis(exp.dis_state.params))):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)
        assert any(
            not np.allclose(a, np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(gen_after_1),
                            jax.tree_util.tree_leaves(exp.gen_params))
        )

    def test_dis_lr_decay_identical_in_scan_and_sequential(self):
        feats, labels = _data(3)
        kw = dict(dis_lr_decay_every=2, dis_lr_decay_rate=0.5)
        seq = GanExperiment(_cfg(**kw))
        seq_d = [float(seq.train_iteration(feats[i], labels[i])["d_loss"])
                 for i in range(3)]
        scan = GanExperiment(_cfg(**kw))
        out = scan.train_iterations(feats, labels)
        np.testing.assert_allclose(
            np.asarray(out["d_loss"]), seq_d, rtol=2e-5, atol=1e-6
        )

    def test_dis_lr_decay_off_is_bit_identical_to_round4_stream(self):
        # the default config must keep the 6-way key split — decay/resample
        # OFF may not perturb the established RNG stream or update math
        feats, labels = _data(2)
        base = GanExperiment(_cfg())
        d0 = np.asarray(base.train_iterations(feats, labels)["d_loss"])
        noop = GanExperiment(_cfg(dis_lr_decay_every=0, dis_lr_decay_rate=0.9))
        d1 = np.asarray(noop.train_iterations(feats, labels)["d_loss"])
        np.testing.assert_array_equal(d0, d1)

    def test_losses_stay_on_device(self):
        exp = GanExperiment(_cfg())
        feats, labels = _data(2)
        out = exp.train_iterations(feats, labels)
        assert out["d_loss"].shape == (2,)
        assert isinstance(out["d_loss"], jax.Array)


class TestRunWindowing:
    def test_window_limit_respects_export_boundaries(self):
        exp = GanExperiment(_cfg(print_every=4, loss_fetch_every=32))
        # export fires after iterations 0, 4, 8, … — each may only END a window
        exp.batch_counter = 0
        assert exp._window_limit(False) == 1
        exp.batch_counter = 1
        assert exp._window_limit(False) == 4  # iterations 1,2,3,4
        exp.batch_counter = 5
        assert exp._window_limit(False) == 4  # 5,6,7,8
        exp.batch_counter = 2
        assert exp._window_limit(False) == 3  # 2,3,4
        # loss_fetch_every caps the window
        exp.config.loss_fetch_every = 2
        exp.batch_counter = 1
        assert exp._window_limit(False) == 2
        # save_models forces sequential
        exp.config.save_models = True
        assert exp._window_limit(False) == 1

    @pytest.mark.slow
    def test_run_windowed_equals_sequential(self, tmp_path):
        """Same data, same seed: the windowed loop must reproduce the
        sequential loop's loss history and export artifacts (exports see the
        same per-iteration states). Horizon kept short (6 iterations)
        because the near-sign-SGD updater amplifies benign f32 reassociation
        between the two compiled programs ~10x every few iterations —
        observed: export divergence 0.0 at iteration 1, 3e-3 at 4, 4e-2 at
        7; a real sequencing bug diverges by O(1) immediately."""
        n_iter = 6
        feats, labels = _data(n_iter, seed=3)
        flat_f = feats.reshape(-1, 784)
        flat_l = labels.reshape(-1, 10)

        results = {}
        for name, fetch_every in (("seq", 1), ("win", 4)):
            out_dir = str(tmp_path / name)
            exp = GanExperiment(
                _cfg(
                    num_iterations=n_iter, print_every=3, loss_fetch_every=fetch_every,
                    output_dir=out_dir,
                )
            )
            it = ArrayDataSetIterator(flat_f, flat_l, batch_size=B)
            results[name] = (exp.run(it), out_dir)

        hist_seq = results["seq"][0]["history"]
        hist_win = results["win"][0]["history"]
        assert len(hist_seq) == len(hist_win) == n_iter
        for a, b in zip(hist_seq, hist_win):
            for k in ("d_loss", "g_loss", "cv_loss"):
                # separately-compiled programs + the near-sign-SGD updater
                # amplify f32 reassociation exponentially over iterations
                # (~0.4% by iteration 9); a mis-sequenced or skipped update
                # diverges by O(1) at the first affected iteration, so 2%
                # still separates bug from noise
                np.testing.assert_allclose(a[k], b[k], rtol=2e-2, atol=2e-2)
        # same export artifacts at the same indices, numerically equal
        seq_dir, win_dir = results["seq"][1], results["win"][1]
        assert sorted(os.listdir(seq_dir)) == sorted(os.listdir(win_dir))
        for fname in os.listdir(seq_dir):
            if not fname.endswith(".csv"):
                continue
            a = np.loadtxt(os.path.join(seq_dir, fname), delimiter=",", ndmin=2)
            b = np.loadtxt(os.path.join(win_dir, fname), delimiter=",", ndmin=2)
            np.testing.assert_allclose(
                a, b, rtol=0, atol=2e-2,
                err_msg=f"export {fname} diverged between windowed and sequential",
            )

    @pytest.mark.slow
    def test_run_handles_ragged_tail_batches(self):
        """A dataset whose size is not a multiple of the batch size produces
        a smaller tail batch each epoch; windows must split around it."""
        rng = np.random.default_rng(7)
        flat_f = rng.random((B * 2 + 3, 784), dtype=np.float32)
        flat_l = np.eye(10, dtype=np.float32)[rng.integers(0, 10, B * 2 + 3)]
        exp = GanExperiment(
            _cfg(num_iterations=6, print_every=1000, loss_fetch_every=8)
        )
        out = exp.run(ArrayDataSetIterator(flat_f, flat_l, batch_size=B))
        assert out["iterations"] == 6
        assert len(out["history"]) == 6
        assert all(np.isfinite(h["d_loss"]) for h in out["history"])


class TestWganDeviceLoop:
    @pytest.mark.slow
    def test_train_rounds_matches_sequential_semantics(self):
        """K scanned WGAN-GP rounds advance the same step counters and
        produce finite, device-resident loss stacks; run() windows engage
        through _supports_device_loop."""
        from gan_deeplearning4j_tpu.harness.wgan_experiment import WganGpExperiment

        cfg = ExperimentConfig(
            model_family="wgan_gp", height=8, width=8, channels=1,
            num_features=64, z_size=4, batch_size_train=4, batch_size_pred=4,
            n_critic=2, num_iterations=10 ** 9, save_models=False,
        )
        exp = WganGpExperiment(cfg)
        assert exp._supports_device_loop
        rng = np.random.default_rng(0)
        feats = rng.random((3, 4, 64), dtype=np.float32)
        out = exp.train_iterations(feats)
        assert out["d_loss"].shape == (3,)
        assert isinstance(out["d_loss"], jax.Array)
        assert np.isfinite(np.asarray(out["d_loss"])).all()
        assert np.isfinite(np.asarray(out["g_loss"])).all()
        # 3 rounds × 2 critic steps; 3 generator steps
        assert int(exp.critic_state.step) == 6
        assert int(exp.gen_state.step) == 3
        # ragged window batch: remainder rows dropped, same policy as the
        # sequential round — the run completes rather than crashing
        out2 = exp.train_iterations(rng.random((2, 5, 64), dtype=np.float32))
        assert out2["d_loss"].shape == (2,)
        assert np.isfinite(np.asarray(out2["d_loss"])).all()

    @pytest.mark.slow
    def test_wgan_run_windowed(self):
        from gan_deeplearning4j_tpu.harness.wgan_experiment import WganGpExperiment

        cfg = ExperimentConfig(
            model_family="wgan_gp", height=8, width=8, channels=1,
            num_features=64, z_size=4, batch_size_train=4, batch_size_pred=4,
            n_critic=2, num_iterations=6, save_models=False,
            print_every=1000, loss_fetch_every=4,
        )
        exp = WganGpExperiment(cfg)
        rng = np.random.default_rng(1)
        it = DeviceResidentIterator(
            rng.random((24, 64), dtype=np.float32), batch_size=4
        )
        out = exp.run(it)
        assert out["iterations"] == 6
        assert len(out["history"]) == 6
        assert all(np.isfinite(h["d_loss"]) for h in out["history"])
        assert "train_rounds" in out["timings"]


class TestParamAveragingDeviceLoop:
    """The faithful-averaging mode's scan window (round-4 VERDICT item 5):
    ``train_iterations`` under ``distributed="param_averaging"`` scans the
    shard_map per-fit-averaging body."""

    def _exp(self, **kw):
        from gan_deeplearning4j_tpu.harness import make_experiment
        from gan_deeplearning4j_tpu.runtime import TpuEnvironment

        mesh = TpuEnvironment().make_mesh()
        base = dict(
            batch_size_train=16, batch_size_pred=16, num_iterations=10 ** 9,
            save_models=False, distributed="param_averaging",
        )
        base.update(kw)
        return make_experiment(ExperimentConfig(**base), mesh=mesh), mesh

    @pytest.mark.slow
    def test_scan_window_runs_and_replicates(self):
        exp, mesh = self._exp()
        assert exp._supports_device_loop
        rng = np.random.default_rng(2)
        feats = rng.random((2, 16, 784), dtype=np.float32)
        labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (2, 16))]
        out = exp.train_iterations(feats, labels)
        assert out["d_loss"].shape == (2,)
        for k in ("d_loss", "g_loss", "cv_loss"):
            assert np.isfinite(np.asarray(out[k])).all()
        # post-averaging invariant: every device's replica is bit-identical
        # for params AND updater state (the reference averages both, D16) —
        # same checker the driver dryrun uses
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from __graft_entry__ import _assert_replicated

        for name, state in (("dis", exp.dis_state), ("gan", exp.gan_state),
                            ("cv", exp.cv_state)):
            _assert_replicated((state.params, state.opt_state), f"{name} state")
        assert int(exp.dis_state.step) == 4  # 2 iterations x 2 dis steps

    @pytest.mark.slow
    def test_scan_chunks_compose(self):
        """scan(K=2) == scan(K=1);scan(K=1) — same program, carried state;
        the per-step RNG derives from the step counter, so chunking cannot
        change the math."""
        rng = np.random.default_rng(3)
        feats = rng.random((2, 16, 784), dtype=np.float32)
        labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (2, 16))]

        one, _ = self._exp()
        l0 = one.train_iterations(feats[:1], labels[:1])
        l1 = one.train_iterations(feats[1:], labels[1:])
        two, _ = self._exp()
        l01 = two.train_iterations(feats, labels)
        np.testing.assert_allclose(
            np.asarray(l01["d_loss"]),
            [float(l0["d_loss"][0]), float(l1["d_loss"][0])],
            rtol=2e-5, atol=1e-6,
        )
        for a, e in zip(
            jax.tree_util.tree_leaves(two.dis_state.params),
            jax.tree_util.tree_leaves(one.dis_state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=0, atol=2e-3
            )

    @pytest.mark.slow
    def test_run_windows_engage_in_averaging_mode(self, tmp_path):
        """The full run() loop under distributed="param_averaging" takes scan
        windows (train_rounds timing phase present), produces finite history
        for every iteration, and exports on cadence — the loop-level
        integration the direct train_iterations tests don't cover."""
        exp, _ = self._exp(
            num_iterations=6, print_every=1000, loss_fetch_every=4,
            output_dir=str(tmp_path),
        )
        rng = np.random.default_rng(5)
        flat_f = rng.random((16 * 6, 784), dtype=np.float32)
        flat_l = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16 * 6)]
        it = DeviceResidentIterator(flat_f, flat_l, batch_size=16)
        out = exp.run(it)
        assert out["iterations"] == 6
        assert len(out["history"]) == 6
        assert all(np.isfinite(h["d_loss"]) for h in out["history"])
        # the scan window actually engaged (vs 6 per-dispatch iterations)
        assert "train_window" in out["timings"]

    @pytest.mark.slow
    def test_averaging_loop_differs_from_pmean_loop(self):
        """The faithful mode is a different algorithm from per-step gradient
        sync (SURVEY §7): local steps diverge before the average."""
        rng = np.random.default_rng(4)
        feats = rng.random((2, 16, 784), dtype=np.float32)
        labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, (2, 16))]
        avg, _ = self._exp()
        avg.train_iterations(feats, labels)
        pm, _ = self._exp(distributed="pmean")
        pm.train_iterations(feats, labels)
        diffs = [
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(
                jax.tree_util.tree_leaves(avg.dis_state.params),
                jax.tree_util.tree_leaves(pm.dis_state.params),
            )
        ]
        assert max(diffs) > 1e-6


class TestDeviceResidentIterator:
    def test_batches_are_device_arrays_and_cover_data(self):
        feats = np.arange(20 * 4, dtype=np.float32).reshape(20, 4) / 80.0
        labels = np.eye(10, dtype=np.float32)[np.arange(20) % 10]
        it = DeviceResidentIterator(feats, labels, batch_size=6)
        seen = []
        while it.has_next():
            b = it.next()
            assert isinstance(b.features, jax.Array)
            seen.append(np.asarray(b.features))
        got = np.concatenate(seen)
        np.testing.assert_array_equal(got, feats)
        it.reset()
        assert it.has_next()

    def test_next_window_slices_match_per_batch_stream(self):
        feats = np.arange(20 * 4, dtype=np.float32).reshape(20, 4) / 80.0
        labels = np.eye(10, dtype=np.float32)[np.arange(20) % 10]
        a = DeviceResidentIterator(feats, labels, batch_size=3)
        b = DeviceResidentIterator(feats, labels, batch_size=3)
        wf, wl = a.next_window(4)
        assert wf.shape == (4, 3, 4)  # pow2 quantized down from avail=6
        seq = [b.next() for _ in range(4)]
        np.testing.assert_array_equal(
            np.asarray(wf), np.stack([np.asarray(s.features) for s in seq])
        )
        np.testing.assert_array_equal(
            np.asarray(wl), np.stack([np.asarray(s.labels) for s in seq])
        )
        # the tail (2 full batches + 2 ragged rows) still streams out
        wf2, _ = a.next_window(100)
        assert wf2.shape[0] == 2
        tail = a.next()
        assert tail.features.shape == (2, 4)  # 20 - 18 rows
        assert not a.has_next()
        # misaligned cursor (mid-batch) refuses windows
        c = DeviceResidentIterator(feats, labels, batch_size=3)
        c.next()
        c.next()  # cursor at 6, aligned: windows OK
        assert c.next_window(1) is not None
        d = DeviceResidentIterator(feats, labels, batch_size=8)
        d.next()
        d.next()  # cursor 16, aligned; one ragged tail of 4 remains
        assert d.next_window(5) is None

    @pytest.mark.slow
    def test_run_uses_next_window_and_matches_sequential(self, tmp_path):
        n_iter = 5
        feats, labels = _data(n_iter, seed=11)
        flat_f = feats.reshape(-1, 784)
        flat_l = labels.reshape(-1, 10)
        hists = {}
        for name, fetch_every in (("seq", 1), ("win", 4)):
            exp = GanExperiment(
                _cfg(num_iterations=n_iter, print_every=1000,
                     loss_fetch_every=fetch_every,
                     output_dir=str(tmp_path / name))
            )
            it = DeviceResidentIterator(flat_f, flat_l, batch_size=B)
            hists[name] = exp.run(it)["history"]
        assert len(hists["seq"]) == len(hists["win"]) == n_iter
        for a, b in zip(hists["seq"], hists["win"]):
            for k in ("d_loss", "g_loss", "cv_loss"):
                np.testing.assert_allclose(a[k], b[k], rtol=2e-2, atol=2e-2)

    def test_shuffle_is_seeded_and_epoch_varying(self):
        feats = np.arange(12, dtype=np.float32).reshape(12, 1)
        a = DeviceResidentIterator(feats, batch_size=12, shuffle=True, seed=1)
        b = DeviceResidentIterator(feats, batch_size=12, shuffle=True, seed=1)
        first_a = np.asarray(a.next().features).ravel()
        first_b = np.asarray(b.next().features).ravel()
        np.testing.assert_array_equal(first_a, first_b)  # same seed, same order
        a.reset()
        second_a = np.asarray(a.next().features).ravel()
        assert not np.array_equal(first_a, second_a)  # epochs reshuffle
        np.testing.assert_array_equal(np.sort(second_a), feats.ravel())
