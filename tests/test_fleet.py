"""fleet/ subsystem tests: circuit-breaker state machine, retry budget,
router proxying + ejection + re-admission against fake HTTP workers, the
draining-restart handshake, and the subprocess fleet drill (slow).

The fake workers are real stdlib HTTP servers with scripted behavior
(answer / die mid-request / shed / hang), so every router path — p2c
pick, retry, breaker trip, half-open probe — runs over real sockets
without a single jax import.
"""

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from gan_deeplearning4j_tpu.fleet import (
    Autoscaler,
    AutoscalerConfig,
    CircuitBreaker,
    FleetManager,
    FleetRouter,
    RetryBudget,
    make_router_server,
)

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ===========================================================================
# fake workers
# ===========================================================================

class _Behavior:
    """Scripted worker behavior, mutable mid-test."""

    def __init__(self):
        self.health = "ok"
        self.generation = 1
        self.queue_depth = 0
        self.in_flight = 0
        self.mode = "ok"  # ok | die | shed
        self.draining = False
        self.lock = threading.Lock()
        self.hits = 0  # /v1 requests that reached this worker
        self.trace_ids = []  # X-Trace-Id headers seen on /v1 requests
        self.payloads = []  # parsed /v1 request bodies (brownout rewrites)
        # what GET /metrics?scope=registry answers (the aggregation feed);
        # None = 404, exercising the labeled-gap path
        self.registry_snapshot = {
            "serve_requests_total": {
                "type": "counter", "help": "x",
                "series": [{"labels": {"kind": "sample", "status": "ok"},
                            "value": 0.0}],
            },
        }
        # what GET /debug/spans answers (the merged-trace feed)
        self.spans = {"traceEvents": []}


class _FakeWorkerHandler(BaseHTTPRequestHandler):
    behavior: _Behavior = None  # bound per spawn

    def _send(self, code, body):
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        b = self.behavior
        if self.path.startswith("/healthz"):
            status = "draining" if b.draining else b.health
            self._send(200, {"status": status, "generation": b.generation})
        elif self.path.startswith("/debug/spans"):
            self._send(200, b.spans)
        elif "scope=registry" in self.path:
            if b.registry_snapshot is None:
                self._send(404, {"status": "error", "error": "no registry"})
            else:
                self._send(200, b.registry_snapshot)
        else:
            self._send(200, {
                "queue_depth": b.queue_depth,
                "generation": b.generation,
                "draining": b.draining,
                "pipeline": {"in_flight": b.in_flight},
                "engine": {"serve_compile_counts": {"sample": 0}},
            })

    def do_POST(self):  # noqa: N802
        b = self.behavior
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n)
        if self.path.startswith("/admin/drain"):
            b.draining = True
            self._send(200, {"status": "ok", "draining": True})
            return
        with b.lock:
            b.hits += 1
            tid = self.headers.get("X-Trace-Id")
            if tid:
                b.trace_ids.append(tid)
            try:
                b.payloads.append(json.loads(raw))
            except ValueError:
                pass
        if b.mode == "die":
            # the mid-request death shape: the connection drops with no
            # response bytes — the client sees a reset/BadStatusLine
            self.close_connection = True
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return
        if b.mode == "shed":
            self._send(503, {"status": "overloaded", "error": "queue full"})
            return
        self._send(200, {"status": "ok", "data": [[1.0, 2.0]]})

    def log_message(self, *args):
        pass


@pytest.fixture
def spawn_worker():
    servers = []

    def spawn():
        behavior = _Behavior()
        handler = type("BoundFake", (_FakeWorkerHandler,),
                       {"behavior": behavior})
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return behavior, srv.server_address[1]

    yield spawn
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def _router(**kw):
    kw.setdefault("request_timeout", 2.0)
    kw.setdefault("backoff_base", 0.005)
    kw.setdefault("backoff_max", 0.01)
    return FleetRouter(**kw)


def _post_sample(router):
    return router.handle("POST", "/v1/sample",
                         json.dumps({"data": [[0.5]]}).encode())


# ===========================================================================
# the circuit breaker
# ===========================================================================

class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


class TestCircuitBreaker:
    def test_init_requires_probe_admission(self):
        br = CircuitBreaker(clock=FakeClock())
        assert br.state == "init" and not br.routable
        assert br.probe_due()
        assert br.probe_result(True) == "admitted"
        assert br.routable

    def test_init_probe_failure_stays_init(self):
        # a warming worker is not FAILING, it is not ready yet — keep
        # probing, never back off
        br = CircuitBreaker(clock=FakeClock())
        br.probe_result(False)
        assert br.state == "init" and br.probe_due()

    def test_consecutive_failures_trip(self):
        br = CircuitBreaker(consecutive_failures=3, clock=FakeClock())
        br.probe_result(True)
        assert br.record(False) is None
        assert br.record(False) is None
        assert br.record(False) == "tripped"
        assert br.state == "open" and not br.routable

    def test_success_resets_the_streak(self):
        br = CircuitBreaker(consecutive_failures=3, clock=FakeClock())
        br.probe_result(True)
        br.record(False)
        br.record(False)
        br.record(True)
        assert br.record(False) is None  # streak restarted
        assert br.state == "closed"

    def test_error_rate_trips_despite_interleaved_successes(self):
        # the flaky-worker path: never 3 in a row, but 60% failing
        br = CircuitBreaker(consecutive_failures=10, error_rate=0.5,
                            rate_window=10, rate_min_samples=10,
                            clock=FakeClock())
        br.probe_result(True)
        tripped = None
        for i in range(20):
            tripped = tripped or br.record(i % 5 == 0)  # 80% failures
        assert tripped == "tripped"

    def test_half_open_single_probe_readmission(self):
        clock = FakeClock()
        br = CircuitBreaker(consecutive_failures=1, reopen_after=5.0,
                            clock=clock)
        br.probe_result(True)
        br.record(False)
        assert br.state == "open" and not br.probe_due()
        clock.now = 5.1
        assert br.state == "half_open" and br.probe_due()
        assert not br.routable  # half-open is probe-only, never routable
        assert br.probe_result(True) == "admitted"
        assert br.routable

    def test_half_open_failure_doubles_backoff(self):
        clock = FakeClock()
        br = CircuitBreaker(consecutive_failures=1, reopen_after=1.0,
                            reopen_max=30.0, clock=clock)
        br.probe_result(True)
        br.record(False)
        clock.now = 1.1
        assert br.state == "half_open"
        br.probe_result(False)
        assert br.state == "open"
        clock.now = 2.1  # 1.0s after the failed probe: doubled, not due
        assert br.state == "open"
        clock.now = 3.2
        assert br.state == "half_open"

    def test_outcomes_while_open_do_not_re_trip(self):
        clock = FakeClock()
        br = CircuitBreaker(consecutive_failures=1, reopen_after=10.0,
                            clock=clock)
        br.probe_result(True)
        br.record(False)
        trips = br.trips
        br.record(False)
        br.record(False)
        assert br.trips == trips

    def test_reset_demands_re_admission(self):
        br = CircuitBreaker(clock=FakeClock())
        br.probe_result(True)
        br.reset()
        assert br.state == "init" and not br.routable


class TestRetryBudget:
    def test_spend_to_exhaustion(self):
        b = RetryBudget(ratio=0.0, burst=2)
        assert b.spend() and b.spend()
        assert not b.spend()

    def test_deposit_caps_at_burst(self):
        b = RetryBudget(ratio=0.5, burst=2)
        for _ in range(10):
            b.deposit()
        assert b.tokens == 2.0
        assert b.spend() and b.spend() and not b.spend()
        b.deposit()  # 0.5 tokens: not enough for a whole retry
        assert not b.spend()
        b.deposit()
        assert b.spend()


# ===========================================================================
# the router (edge cases from the satellite checklist)
# ===========================================================================

class TestRouterProxy:
    def test_round_trip_and_p2c_distribution(self, spawn_worker):
        b1, p1 = spawn_worker()
        b2, p2 = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        for _ in range(20):
            status, payload = _post_sample(r)
            assert status == 200
            assert json.loads(payload)["data"] == [[1.0, 2.0]]
        # p2c with equal load must not starve either worker
        assert b1.hits > 0 and b2.hits > 0
        assert r.metrics()["ok"] == 20

    def test_worker_dies_mid_request_client_still_gets_one_answer(
            self, spawn_worker):
        dying, p1 = spawn_worker()
        healthy, p2 = spawn_worker()
        dying.mode = "die"
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        answered = 0
        for _ in range(12):
            status, payload = _post_sample(r)
            assert status == 200, payload  # every request got ONE answer
            answered += 1
        assert answered == 12
        m = r.metrics()
        # the deaths were absorbed by retries, each consuming budget
        assert dying.hits >= 1
        assert m["retries"] >= dying.hits
        assert m["retry_budget_tokens"] < r.budget.burst

    def test_all_workers_ejected_answers_fast_503(self, spawn_worker):
        b1, p1 = spawn_worker()
        b2, p2 = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        for w in r.workers():
            w.breaker.eject()
        t0 = time.monotonic()
        status, payload = _post_sample(r)
        elapsed = time.monotonic() - t0
        assert status == 503
        assert b"no routable worker" in payload
        assert elapsed < 0.5  # O(1) shed, no dead-socket wait
        assert r.metrics()["no_worker"] == 1

    def test_shed_storm_exhausts_budget_to_honest_503(self, spawn_worker):
        b1, p1 = spawn_worker()
        b2, p2 = spawn_worker()
        b1.mode = b2.mode = "shed"
        r = _router(retry_ratio=0.0, retry_burst=1.0, max_attempts=4)
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        status, payload = _post_sample(r)
        assert status == 503
        assert b"retry budget exhausted" in payload
        m = r.metrics()
        assert m["budget_exhausted"] == 1
        assert m["retries"] == 1  # the single token, then the honest 503

    def test_no_worker_retry_refunds_its_budget_token(self, spawn_worker):
        # 2 workers, one ejected: a connect-failure on the survivor finds
        # nowhere to retry — the token spent for that retry must come
        # back, or a brownout drains the shared bucket on retries that
        # never happen
        dying, p1 = spawn_worker()
        _, p2 = spawn_worker()
        dying.mode = "die"
        r = _router(breaker_kwargs={"consecutive_failures": 100})
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        r.worker("w1").breaker.eject()
        tokens_before = r.budget.tokens
        status, payload = _post_sample(r)
        assert status == 503
        assert b"no routable worker" in payload
        # deposit happens per request; the retry token was refunded
        assert r.budget.tokens >= tokens_before
        assert r.metrics()["no_worker"] == 1

    def test_self_drained_worker_leaves_the_pool(self, spawn_worker):
        # a worker drained directly (POST /admin/drain on the worker, not
        # through the manager) reports draining in /metrics: the router
        # must stop routing to it even though its breaker stays closed
        behavior, p1 = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.health_pass()
        assert r.worker("w0").routable
        behavior.draining = True  # the worker marks itself
        r.health_pass()  # scrape picks the flag up
        assert not r.worker("w0").routable
        status, payload = _post_sample(r)
        assert status == 503
        assert b"no routable worker" in payload

    def test_ejection_then_half_open_readmission(self, spawn_worker):
        flaky, p1 = spawn_worker()
        steady, p2 = spawn_worker()
        flaky.mode = "die"
        r = _router(breaker_kwargs={"consecutive_failures": 1,
                                    "reopen_after": 0.05})
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        for _ in range(6):
            status, _ = _post_sample(r)
            assert status == 200
        ref = r.worker("w0")
        assert not ref.routable  # ejected after its first death
        assert r.metrics()["ejections"] == 1
        # worker recovers; after the reopen window one probe re-admits it
        flaky.mode = "ok"
        time.sleep(0.06)
        assert ref.breaker.state == "half_open"
        r.health_pass()
        assert ref.routable
        hits_before = flaky.hits
        for _ in range(10):
            assert _post_sample(r)[0] == 200
        assert flaky.hits > hits_before  # traffic actually returned

    def test_warming_worker_admitted_only_when_ok(self, spawn_worker):
        b, p = spawn_worker()
        b.health = "warming"
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        assert not r.worker("w0").routable
        status, _ = _post_sample(r)
        assert status == 503  # nothing admittable yet
        b.health = "ok"
        r.health_pass()
        assert r.worker("w0").routable
        assert _post_sample(r)[0] == 200

    def test_draining_worker_gets_no_new_requests(self, spawn_worker):
        b1, p1 = spawn_worker()
        b2, p2 = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        r.mark_draining("w0")
        for _ in range(10):
            assert _post_sample(r)[0] == 200
        assert b1.hits == 0 and b2.hits == 10
        # healthz shows the drain; un-draining restores routing
        snap = [w for w in r.healthz()["workers"] if w["id"] == "w0"][0]
        assert snap["draining"] and not snap["routable"]
        r.mark_draining("w0", False)
        for _ in range(20):
            if _post_sample(r)[0] == 200 and b1.hits:
                break
        assert b1.hits > 0

    def test_hung_scrape_ejects_an_idle_worker(self, spawn_worker):
        # passive ejection must not require traffic: the health loop's
        # scrape failing repeatedly trips the breaker too
        b, p = spawn_worker()
        r = _router(probe_timeout=0.5,
                    breaker_kwargs={"consecutive_failures": 2})
        ref = r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        assert ref.routable
        # simulate the hang by pointing the scrape at a dead port
        ref.base_url = "http://127.0.0.1:1"
        r.health_pass()
        r.health_pass()
        assert not ref.routable

    def test_http_front_end_serves_health_and_proxy(self, spawn_worker):
        import urllib.request

        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()  # pass 1 admits the worker...
        r.health_pass()  # ...pass 2 scrapes its /metrics (generation)
        srv = make_router_server(r, port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5.0) as resp:
                health = json.loads(resp.read())
            assert health["status"] == "ok" and health["routable"] == 1
            assert health["generation"] == 1
            req = urllib.request.Request(
                f"{base}/v1/sample",
                data=json.dumps({"data": [[0.5]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                body = json.loads(resp.read())
            assert body["status"] == "ok"
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=5.0) as resp:
                metrics = json.loads(resp.read())
            assert metrics["ok"] == 1
        finally:
            srv.shutdown()
            srv.server_close()


# ===========================================================================
# the manager's draining restart (fake processes, real drain scrapes)
# ===========================================================================

class _FakeProc:
    def __init__(self):
        self._alive = True
        self.pid = 4242
        self.stopped = 0

    def alive(self):
        return self._alive

    def stop(self, grace: float = 10.0):
        self._alive = False
        self.stopped += 1


class TestDrainingRestart:
    def _manager(self, tmp_path, router, port, **kw):
        kw.setdefault("drain_timeout", 0.6)
        kw.setdefault("warm_timeout", 5.0)
        spawned = []

        def spawn(slot, bundle_path):
            proc = _FakeProc()
            spawned.append((slot.id, bundle_path, proc))
            return proc

        mgr = FleetManager(router, str(tmp_path / "store"), num_workers=1,
                           ports=[port], spawn=spawn, **kw)
        mgr._spawned = spawned
        return mgr

    def test_drain_completes_when_pipeline_empties(self, tmp_path,
                                                   spawn_worker):
        behavior, port = spawn_worker()
        behavior.in_flight = 2
        r = _router()
        mgr = self._manager(tmp_path, r, port, drain_timeout=5.0)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")

        def empty_soon():
            time.sleep(0.3)
            behavior.in_flight = 0
            behavior.queue_depth = 0

        threading.Thread(target=empty_soon, daemon=True).start()
        assert mgr.drain_worker(slot) is True
        assert behavior.draining  # the worker was told (POST /admin/drain)
        assert r.worker("w0").draining  # and unrouted at the router

    def test_drain_with_stuck_inflight_is_bounded_then_forced(
            self, tmp_path, spawn_worker):
        behavior, port = spawn_worker()
        behavior.in_flight = 1  # never drains
        r = _router()
        mgr = self._manager(tmp_path, r, port, drain_timeout=0.5)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        t0 = time.monotonic()
        assert mgr.drain_worker(slot) is False
        assert time.monotonic() - t0 < 3.0  # bounded, not a hang

    def test_rotate_forces_restart_and_waits_for_readmission(
            self, tmp_path, spawn_worker):
        behavior, port = spawn_worker()
        behavior.in_flight = 1  # stuck: the rotation must force it
        r = _router(probe_interval=0.05)
        mgr = self._manager(tmp_path, r, port, drain_timeout=0.3)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        old_proc = slot.process
        # a relaunched process starts fresh: not draining, empty pipeline
        # (the fake worker server survives the "restart", so reset it at
        # spawn time the way a real exec would)
        orig_spawn = mgr._spawn

        def spawn_fresh(slot_, bundle_path):
            behavior.draining = False
            behavior.in_flight = 0
            return orig_spawn(slot_, bundle_path)

        mgr._spawn = spawn_fresh
        r.start_health_loop()
        try:
            ok = mgr.rotate_worker(slot, "bundle-b")
        finally:
            r.stop()
        assert ok  # relaunched worker was re-admitted (healthz ok)
        assert old_proc.stopped == 1  # the stuck process was torn down
        assert slot.process is not old_proc
        assert slot.bundle_path == "bundle-b"
        assert slot.restarts == 1
        assert not r.worker("w0").draining  # rotation cleared the mark

    def test_probe_cmd_pins_feature_space_to_boot_incumbent(self, tmp_path):
        # dis-feature probes must embed in ONE classifier space across
        # rolls: the pin is the boot incumbent, not the rolling bundle
        r = _router()
        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=1,
                           ports=[1], spawn=lambda s, b: _FakeProc(),
                           canary_data="canary.npz",
                           canary_feature="dis_features")
        mgr._feature_bundle = "bundle-gen0"  # pinned at boot
        mgr.bundle_path = "bundle-gen5"  # the fleet rolled since
        cmd = mgr._probe_cmd("bundle-gen6")
        assert cmd[cmd.index("--feature-bundle") + 1] == "bundle-gen0"

    def test_halted_roll_rolls_back_already_rotated_workers(
            self, tmp_path, spawn_worker):
        # 2-worker fleet rolling to a candidate: w0 rotates fine, w1
        # fails to come back healthy — the candidate is quarantined AND
        # w0 (already on the candidate) must roll back to the incumbent,
        # never keep serving a quarantined generation
        from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate

        _, p0 = spawn_worker()
        _, p1 = spawn_worker()
        r = _router()
        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=2,
                           ports=[p0, p1],
                           spawn=lambda slot, bundle: _FakeProc(),
                           drain_timeout=0.2, warm_timeout=0.2)
        for slot in mgr.slots:
            mgr._launch(slot, "bundle-old")
        mgr.generation, mgr.bundle_path = 1, "bundle-old"
        discards = []
        mgr.watcher = type("W", (), {"discard": staticmethod(
            lambda cand, reason, quarantine=False: discards.append(
                (cand.generation, quarantine)))})()
        rotations = []

        def fake_rotate(slot, bundle_path):
            rotations.append((slot.id, bundle_path))
            if slot is mgr.slots[1] and bundle_path == "bundle-new":
                return False  # w1 cannot boot the candidate
            slot.bundle_path = bundle_path
            return True

        mgr.rotate_worker = fake_rotate
        cand = BundleCandidate(path="bundle-new", generation=2,
                               token="gen-2", manifest={})
        assert mgr._admit_and_roll(cand) is True
        assert discards == [(2, True)]  # quarantined fleet-wide, once
        assert ("w0", "bundle-old") in rotations  # w0 rolled back
        assert all(s.bundle_path == "bundle-old" for s in mgr.slots)
        assert mgr.generation == 1  # fleet stays on the incumbent
        assert mgr.status()["state"] == "halted"

    def test_feature_repin_falls_back_to_candidate_when_incumbent_gone(
            self, tmp_path, spawn_worker):
        # dis_features mode with BOTH the pinned feature bundle and the
        # incumbent GC'd: the re-pin must land on the candidate (the only
        # embedding space still on disk) — a missing pin would fail every
        # candidate probe and quarantine good generations forever
        from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate

        _, p0 = spawn_worker()
        r = _router()
        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=1,
                           ports=[p0],
                           spawn=lambda slot, bundle: _FakeProc(),
                           canary_data="canary.npz",
                           canary_feature="dis_features",
                           drain_timeout=0.2, warm_timeout=0.2)
        mgr._launch(mgr.slots[0], "bundle-old")
        mgr.generation = 1
        mgr.bundle_path = str(tmp_path / "gc-ed-incumbent")  # gone
        mgr._feature_bundle = str(tmp_path / "gc-ed-pin")  # gone too
        cand_dir = tmp_path / "cand"
        cand_dir.mkdir()
        mgr._sidecar_probe = lambda path: {"fid": 1.0, "accuracy": None}
        mgr.rotate_worker = lambda slot, bundle: True
        cand = BundleCandidate(path=str(cand_dir), generation=2,
                               token="gen-2", manifest={})
        assert mgr._admit_and_roll(cand) is True
        assert mgr._feature_bundle == str(cand_dir)
        assert mgr.generation == 2  # rolled (ungated — no baseline exists)
        events = [e["event"] for e in mgr.events]
        assert "ungated_roll" in events

    def test_halted_roll_keeps_incumbent_probe_baseline(
            self, tmp_path, spawn_worker):
        # the candidate passes the canary but the roll halts: the cached
        # incumbent baseline must survive — rolling the cache forward
        # before the roll completes would discard the real incumbent's
        # probe (and, once its bundle is GC'd, admit the next candidate
        # ungated despite a baseline having been measured)
        from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate

        _, p0 = spawn_worker()
        r = _router()
        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=1,
                           ports=[p0],
                           spawn=lambda slot, bundle: _FakeProc(),
                           canary_data="canary.npz",
                           drain_timeout=0.2, warm_timeout=0.2)
        mgr._launch(mgr.slots[0], "bundle-old")
        mgr.generation, mgr.bundle_path = 1, "bundle-old"
        incumbent_probe = {"fid": 1.0, "accuracy": 0.9}
        mgr._incumbent_probes = {1: incumbent_probe}
        mgr._sidecar_probe = lambda path: {"fid": 1.0, "accuracy": 0.9}
        mgr.watcher = type("W", (), {"discard": staticmethod(
            lambda cand, reason, quarantine=False: None)})()
        mgr.rotate_worker = lambda slot, bundle: bundle != "bundle-new"
        cand = BundleCandidate(path="bundle-new", generation=2,
                               token="gen-2", manifest={})
        assert mgr._admit_and_roll(cand) is True
        assert mgr.status()["state"] == "halted"
        assert mgr._incumbent_probes == {1: incumbent_probe}

    def test_stop_mid_roll_neither_quarantines_nor_converges(
            self, tmp_path, spawn_worker):
        # shutdown killing a worker mid-rotation must read as
        # infrastructure, not a canary verdict: the candidate generation
        # is NOT quarantined and the fleet does not claim convergence
        from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate

        _, p0 = spawn_worker()
        r = _router()
        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=1,
                           ports=[p0],
                           spawn=lambda slot, bundle: _FakeProc(),
                           drain_timeout=0.2, warm_timeout=0.2)
        mgr._launch(mgr.slots[0], "bundle-old")
        mgr.generation, mgr.bundle_path = 1, "bundle-old"
        discards = []
        mgr.watcher = type("W", (), {"discard": staticmethod(
            lambda cand, reason, quarantine=False: discards.append(
                cand.generation))})()

        def rotate_during_shutdown(slot, bundle):
            mgr._stop.set()  # stop() landed while this rotation ran
            return False

        mgr.rotate_worker = rotate_during_shutdown
        cand = BundleCandidate(path="bundle-new", generation=2,
                               token="gen-2", manifest={})
        assert mgr._admit_and_roll(cand) is True
        assert discards == []  # no quarantine verdict on shutdown
        assert mgr.generation == 1  # and no convergence claim
        events = [e["event"] for e in mgr.events]
        assert "roll_interrupted" in events

    def test_supervise_relaunches_a_dead_process(self, tmp_path,
                                                 spawn_worker):
        behavior, port = spawn_worker()
        r = _router()
        mgr = self._manager(tmp_path, r, port)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        mgr.bundle_path = "bundle-a"
        # the worker earns admission (probe -> closed) and supervision
        # observes it: a ROUTABLE worker's death relaunches immediately
        # (the spawn-failure backoff is only for never-admitted boots)
        r.health_pass()
        mgr._supervise_once()
        assert slot.ever_routable
        slot.process._alive = False  # SIGKILL shape
        mgr._supervise_once()
        assert slot.restarts == 1
        assert slot.process.alive()
        assert r.worker("w0").breaker.state == "init"  # must re-earn entry

    def test_supervise_restarts_a_worker_stuck_in_init(self, tmp_path,
                                                       spawn_worker):
        # SIGSTOP (or a wedged warmup) BEFORE the first admission: the
        # breaker sits in init forever — init probe failures never trip
        # it — so hang detection must bound the launch→admission window
        _, port = spawn_worker()
        r = _router()
        mgr = self._manager(tmp_path, r, port, warm_timeout=0.1)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        mgr.bundle_path = "bundle-a"
        assert r.worker("w0").breaker.state == "init"
        mgr._supervise_once()  # inside the allowance: left alone
        assert slot.restarts == 0
        time.sleep(0.15)
        mgr._supervise_once()
        assert slot.restarts == 1  # never-healthy worker forced out
        # the relaunch re-arms the clock: no immediate second restart
        mgr._supervise_once()
        assert slot.restarts == 1


# ===========================================================================
# the subprocess drill (slow)
# ===========================================================================

@pytest.mark.slow
class TestFleetDrill:
    def test_drill_smoke(self, tmp_path):
        out = tmp_path / "fleet_drill.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleet_drill.py"),
             "--smoke", "--output", str(out),
             "--workdir", str(tmp_path / "work")],
            cwd=REPO, capture_output=True, text=True, timeout=1800,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (
            f"fleet drill breached invariants:\n{proc.stdout[-4000:]}\n"
            f"{proc.stderr[-2000:]}")
        payload = json.loads(out.read_text())
        assert payload["ok"]
        assert payload["invariants"]["exactly_one_answer_zero_lost"]
        assert payload["invariants"]["poison_never_served"]

    def test_autoscale_drill_smoke(self, tmp_path):
        # the elasticity story end-to-end against real subprocesses:
        # ~10x burst -> grow to max (mid-resize SIGKILL recovered) ->
        # brownout only at max -> quiesce -> drain back to min, with the
        # zero-lost ledger and bounded p99 held throughout
        out = tmp_path / "fleet_autoscale.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleet_drill.py"),
             "--smoke", "--autoscale", "--output", str(out),
             "--workdir", str(tmp_path / "work")],
            cwd=REPO, capture_output=True, text=True, timeout=1500,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (
            f"autoscale drill breached invariants:\n{proc.stdout[-4000:]}\n"
            f"{proc.stderr[-2000:]}")
        payload = json.loads(out.read_text())
        assert payload["ok"]
        assert payload["invariants"]["exactly_one_answer_zero_lost"]
        assert payload["invariants"]["brownout_only_at_max"]
        assert payload["invariants"]["quiesce_shrinks_to_min"]

    def test_alerts_drill_smoke(self, tmp_path):
        # the fire-and-resolve story end-to-end against real
        # subprocesses: SIGKILL -> worker_down with the dead pid + an
        # exemplar trace id resolvable in the merged /debug/trace,
        # overload -> latency anomaly, quiesce -> both resolve, zero
        # false fires in the calm audit windows, zero-lost ledger
        out = tmp_path / "fleet_alerts.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fleet_drill.py"),
             "--smoke", "--alerts", "--output", str(out),
             "--workdir", str(tmp_path / "work")],
            cwd=REPO, capture_output=True, text=True, timeout=1500,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (
            f"alerts drill breached invariants:\n{proc.stdout[-4000:]}\n"
            f"{proc.stderr[-2000:]}")
        payload = json.loads(out.read_text())
        assert payload["ok"]
        assert payload["invariants"]["worker_down_fires"]
        assert payload["invariants"]["exemplar_trace_in_merged_trace"]
        assert payload["invariants"]["latency_anomaly_fires"]
        assert payload["invariants"]["all_alerts_resolve"]
        assert payload["results"]["false_fires"] == 0


# ===========================================================================
# fleet observability: trace propagation, aggregation, SLO, staleness
# (ISSUE-11)
# ===========================================================================

class TestTracePropagation:
    def test_client_trace_id_forwarded_to_worker(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        status, _ = r.handle("POST", "/v1/sample",
                             json.dumps({"data": [[0.5]]}).encode(),
                             trace_id="client-abc.1")
        assert status == 200
        assert b.trace_ids == ["client-abc.1"]

    def test_minted_id_when_client_sends_none_or_garbage(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        r.handle("POST", "/v1/sample",
                 json.dumps({"data": [[0.5]]}).encode())
        r.handle("POST", "/v1/sample",
                 json.dumps({"data": [[0.5]]}).encode(),
                 trace_id="bad id\nwith junk")
        assert len(b.trace_ids) == 2
        for tid in b.trace_ids:
            assert tid and "\n" not in tid and " " not in tid
        assert "bad id\nwith junk" not in b.trace_ids

    def test_retried_request_carries_one_id_across_workers(
            self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.trace import TRACER

        shedding, p1 = spawn_worker()
        healthy, p2 = spawn_worker()
        shedding.mode = "shed"
        TRACER.enable()
        r = _router(seed=3)
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        # drive until a request lands on the shedder first and is retried
        # onto the healthy worker (p2c randomness; bounded attempts)
        for i in range(40):
            tid = f"retry-case-{i}"
            status, _ = r.handle(
                "POST", "/v1/sample",
                json.dumps({"data": [[0.5]]}).encode(), trace_id=tid)
            assert status == 200
            if tid in shedding.trace_ids and tid in healthy.trace_ids:
                break
        else:
            pytest.fail("no request was retried across both workers")
        # the router's own spans carry the same id: route + 2 attempts
        events = [e for e in TRACER.events()
                  if (e.get("args") or {}).get("trace_id") == tid]
        names = {e["name"] for e in events}
        assert "fleet.route" in names
        assert "fleet.attempt" in names
        attempts = [e for e in events if e["name"] == "fleet.attempt"]
        assert {a["args"]["worker"] for a in attempts} == {"w0", "w1"}

    def test_http_front_end_echoes_trace_id_header(self, spawn_worker):
        import http.client as hc

        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        srv = make_router_server(r, port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            conn = hc.HTTPConnection("127.0.0.1", srv.server_address[1],
                                     timeout=5.0)
            conn.request("POST", "/v1/sample",
                         body=json.dumps({"data": [[0.5]]}),
                         headers={"X-Trace-Id": "hdr-1"})
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            assert resp.getheader("X-Trace-Id") == "hdr-1"
            conn.close()
            assert b.trace_ids == ["hdr-1"]
        finally:
            srv.shutdown()
            srv.server_close()


class TestFleetAggregationEndpoints:
    def test_fleet_scope_merges_workers_and_router(self, spawn_worker):
        b1, p1 = spawn_worker()
        b2, p2 = spawn_worker()
        b1.registry_snapshot["serve_requests_total"]["series"][0][
            "value"] = 7.0
        b2.registry_snapshot["serve_requests_total"]["series"][0][
            "value"] = 5.0
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", f"http://127.0.0.1:{p2}")
        r.health_pass()
        snap = r.fleet_metrics_snapshot()
        assert snap["_fleet"]["gaps"] == []
        assert sorted(snap["_fleet"]["members"]) == ["router", "w0", "w1"]
        [series] = snap["serve_requests_total"]["series"]
        assert series["value"] == 12.0
        # the router's own registry families ride along
        assert "fleet_slo_burn_rate" in snap

    def test_failed_worker_scrape_is_a_labeled_gap(self, spawn_worker):
        b1, p1 = spawn_worker()
        b1.registry_snapshot = None  # scrape 404s
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p1}")
        r.add_worker("w1", "http://127.0.0.1:1")  # nothing listens
        r.health_pass()
        snap = r.fleet_metrics_snapshot()
        assert snap["_fleet"]["gaps"] == ["w0", "w1"]
        up = {s["labels"]["worker"]: s["value"]
              for s in snap["fleet_member_up"]["series"]}
        assert up["w0"] == 0.0 and up["w1"] == 0.0 and up["router"] == 1.0

    def test_http_fleet_scope_json_and_prom(self, spawn_worker):
        import urllib.request

        b, p = spawn_worker()
        b.registry_snapshot["serve_requests_total"]["series"][0][
            "value"] = 3.0
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        srv = make_router_server(r, port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            with urllib.request.urlopen(f"{base}/metrics?scope=fleet",
                                        timeout=5.0) as resp:
                snap = json.loads(resp.read())
            assert snap["serve_requests_total"]["series"][0]["value"] == 3.0
            with urllib.request.urlopen(
                    f"{base}/metrics?scope=fleet&format=prom",
                    timeout=5.0) as resp:
                assert "text/plain" in resp.getheader("Content-Type")
                text = resp.read().decode()
            assert 'serve_requests_total{kind="sample",status="ok"} 3' in text
            assert 'fleet_member_up{worker="w0"} 1' in text
        finally:
            srv.shutdown()
            srv.server_close()

    def test_debug_trace_merges_router_and_worker_spans(self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.trace import TRACER

        b, p = spawn_worker()
        b.spans = {"traceEvents": [
            {"name": "serve.request", "ph": "X", "ts": 1.0, "dur": 2.0,
             "pid": 4242, "tid": 1, "args": {"trace_id": "t-1"}},
        ]}
        TRACER.enable()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        status, _ = r.handle("POST", "/v1/sample",
                             json.dumps({"data": [[0.5]]}).encode(),
                             trace_id="t-1")
        assert status == 200
        merged = r.fleet_trace()
        names = {e["name"] for e in merged["traceEvents"]}
        assert "serve.request" in names  # the worker's span
        assert "fleet.route" in names    # the router's own
        pids = {e["pid"] for e in merged["traceEvents"]
                if (e.get("args") or {}).get("trace_id") == "t-1"}
        assert 4242 in pids and len(pids) >= 2
        assert merged["metadata"]["gaps"] == []

    def test_debug_trace_tolerates_dead_worker(self, spawn_worker):
        r = _router()
        r.add_worker("w0", "http://127.0.0.1:1")
        merged = r.fleet_trace()
        assert merged["metadata"]["gaps"] == ["w0"]
        assert isinstance(merged["traceEvents"], list)


class TestSLOAndStalenessSurfaces:
    def test_healthz_surfaces_slo_and_scrape_age(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()  # pass 1 admits (probe); pass 2 scrapes /metrics
        r.health_pass()
        for _ in range(5):
            assert _post_sample(r)[0] == 200
        body = r.healthz()
        assert body["slo"]["totals"]["requests"] == 5
        assert body["slo"]["totals"]["failed"] == 0
        [worker] = body["workers"]
        assert isinstance(worker["last_scrape_age_s"], float)
        assert worker["last_scrape_age_s"] >= 0.0

    def test_scrape_age_absent_before_first_scrape(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        [worker] = [w.snapshot() for w in r.workers()]
        assert worker["last_scrape_age_s"] is None

    def test_brownout_burns_availability(self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig

        r = _router(slo_config=SLOConfig(availability_target=0.9,
                                         fast_window_s=30.0,
                                         slow_window_s=60.0))
        # no workers registered: every request is an honest 503
        for _ in range(10):
            status, _ = _post_sample(r)
            assert status == 503
        slo = r.healthz()["slo"]
        assert slo["ok"] is False
        assert slo["burn_rates"]["availability"]["fast"] == pytest.approx(
            1.0 / (1.0 - 0.9))
        assert slo["totals"] == {"requests": 10, "failed": 10, "slow": 0}


class TestManagerTelemetryFlag:
    def test_worker_cmd_carries_telemetry(self, tmp_path):
        r = _router()
        m = FleetManager(r, str(tmp_path), num_workers=1, ports=[1],
                         spawn=lambda slot, bundle: None, telemetry=True)
        cmd = m._worker_cmd(m.slots[0], "/bundle")
        assert "--telemetry" in cmd
        m2_router = _router()
        m2 = FleetManager(m2_router, str(tmp_path), num_workers=1, ports=[2],
                          spawn=lambda slot, bundle: None)
        assert "--telemetry" not in m2._worker_cmd(m2.slots[0], "/bundle")


class TestReviewHardening:
    def test_fleet_json_is_strict_json_with_empty_slo_windows(
            self, spawn_worker):
        # an idle router's SLO gauges hold NaN (empty windows, fails
        # closed) — the JSON fleet surface must carry null, not a NaN
        # token strict parsers reject
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        srv = make_router_server(r, port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            import urllib.request

            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.server_address[1]}"
                    f"/metrics?scope=fleet", timeout=5.0) as resp:
                text = resp.read().decode()
            # parse with NaN acceptance DISABLED — the strict-parser view
            body = json.loads(
                text, parse_constant=lambda c: pytest.fail(
                    f"non-JSON constant {c!r} in fleet payload"))
            burn = {
                (s["labels"]["objective"], s["labels"]["window"]):
                    s["value"]
                for s in body["fleet_slo_burn_rate"]["series"]
            }
            assert burn[("availability", "fast")] is None
        finally:
            srv.shutdown()
            srv.server_close()

    def test_route_exception_records_slo_failure(self, monkeypatch):
        r = _router()

        def boom(method, path, body):
            raise RuntimeError("router bug")

        monkeypatch.setattr(r, "_route", boom)
        with pytest.raises(RuntimeError):
            r.handle("POST", "/v1/sample", b"{}")
        slo = r.slo.snapshot()
        assert slo["totals"] == {"requests": 1, "failed": 1, "slow": 0}

    def test_fleet_snapshot_with_no_workers(self):
        r = _router()
        snap = r.fleet_metrics_snapshot()
        assert snap["_fleet"]["members"] == ["router"]
        assert snap["_fleet"]["gaps"] == []


# ===========================================================================
# autoscaler + brownout + resize edges (ISSUE-13)
# ===========================================================================

def _signals(routable=1, queue=0, inflight=0, burn=0.0):
    """A healthy scrape: burn on both windows of both objectives."""
    return {
        "routable": routable, "queue_depth": queue, "in_flight": inflight,
        "burn_rates": {
            "availability": {"fast": burn, "slow": burn},
            "latency": {"fast": burn, "slow": burn},
        },
    }


class _ScriptedScrape:
    def __init__(self):
        self.value = _signals()

    def __call__(self):
        return self.value


class TestAutoscalerDecisions:
    def _fleet(self, tmp_path, *, slots=1, spawn=None, **cfg_kw):
        cfg_kw.setdefault("min_workers", 1)
        cfg_kw.setdefault("max_workers", 3)
        cfg_kw.setdefault("up_consecutive", 2)
        cfg_kw.setdefault("down_consecutive", 2)
        cfg_kw.setdefault("interval_s", 1.0)
        cfg_kw.setdefault("up_cooldown_s", 5.0)
        cfg_kw.setdefault("down_cooldown_s", 5.0)
        cfg_kw.setdefault("brownout_exit_ticks", 2)
        r = _router()
        mgr = FleetManager(
            r, str(tmp_path / "store"), num_workers=slots,
            ports=list(range(20001, 20001 + slots)),
            spawn=spawn or (lambda slot, bundle: _FakeProc()))
        mgr.bundle_path = "bundle-a"
        clock = FakeClock()
        scrape = _ScriptedScrape()
        auto = Autoscaler(mgr, AutoscalerConfig(**cfg_kw),
                          clock=clock, scrape=scrape)
        mgr.autoscaler = auto
        return mgr, auto, clock, scrape

    def _tick(self, auto, clock, interval=1.0):
        clock.now += interval
        return auto.tick()

    def test_unreachable_scrape_fails_closed_and_resets_streaks(
            self, tmp_path):
        # the satellite edge: an autoscaler that cannot see the router
        # HOLDS — and evidence gathered before the blackout is stale, so
        # the streak restarts from zero afterwards
        mgr, auto, clock, scrape = self._fleet(tmp_path)
        scrape.value = _signals(routable=1, queue=8)  # overloaded tick 1/2
        assert self._tick(auto, clock) == "hold"
        scrape.value = None  # router unreachable
        assert self._tick(auto, clock) == "hold_no_signals"
        assert len(mgr.slots) == 1  # held, not resized
        scrape.value = _signals(routable=1, queue=8)
        assert self._tick(auto, clock) == "hold"  # streak restarted
        assert self._tick(auto, clock) == "up"  # full streak re-earned

    def test_missing_or_nan_signals_hold(self, tmp_path):
        mgr, auto, clock, scrape = self._fleet(tmp_path)
        scrape.value = {"routable": 1, "queue_depth": None, "in_flight": 0}
        assert self._tick(auto, clock) == "hold_no_signals"
        scrape.value = {"routable": 1, "queue_depth": float("nan"),
                        "in_flight": 0}
        assert self._tick(auto, clock) == "hold_no_signals"
        # every field fails closed the same way — a NaN in_flight must
        # not slip through as pressure=NaN (which compares False both
        # ways and would quietly accumulate calm ticks)
        scrape.value = {"routable": 1, "queue_depth": 0,
                        "in_flight": float("nan")}
        assert self._tick(auto, clock) == "hold_no_signals"
        scrape.value = {"routable": None, "queue_depth": 0, "in_flight": 0}
        assert self._tick(auto, clock) == "hold_no_signals"
        # NaN burn rates (empty SLO windows) qualify nothing: with calm
        # queues they neither scale up nor block a hold
        scrape.value = {
            "routable": 1, "queue_depth": 9, "in_flight": 0,
            "burn_rates": {
                "availability": {"fast": float("nan"), "slow": 9.0},
                "latency": {"fast": float("nan"), "slow": float("nan")},
            },
        }
        # pressure 9 still qualifies the tick (queues are real data) —
        # but a NaN-mixed burn alone must not
        assert self._tick(auto, clock) == "hold"
        assert auto.status()["up_streak"] == 1

    def test_sustained_pressure_scales_up_with_cooldown(self, tmp_path):
        spawned = []

        def spawn(slot, bundle):
            spawned.append((slot.id, bundle))
            return _FakeProc()

        mgr, auto, clock, scrape = self._fleet(tmp_path, spawn=spawn)
        scrape.value = _signals(routable=1, queue=8)
        assert self._tick(auto, clock) == "hold"  # hysteresis tick 1/2
        assert self._tick(auto, clock) == "up"
        assert len(mgr.slots) == 2
        assert spawned == [("w1", "bundle-a")]  # current bundle, new id
        # cooldown: pressure stays high but the next resize must wait
        scrape.value = _signals(routable=2, queue=12)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "hold_cooldown"
        clock.now += 5.0  # past up_cooldown_s
        assert self._tick(auto, clock) == "up"
        assert len(mgr.slots) == 3

    def test_burn_rate_alone_scales_up(self, tmp_path):
        # shallow queues but the SLO burning on BOTH windows: the fleet
        # is failing its objectives — add capacity
        mgr, auto, clock, scrape = self._fleet(tmp_path)
        scrape.value = _signals(routable=1, queue=0, burn=2.0)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "up"
        assert len(mgr.slots) == 2

    def test_calm_scales_down_to_min_and_stops(self, tmp_path, spawn_worker):
        # two live fake workers so scale-down's drain path has a real
        # /metrics to watch; both idle -> the drain completes instantly
        b0, p0 = spawn_worker()
        b1, p1 = spawn_worker()
        mgr, auto, clock, scrape = self._fleet(tmp_path, slots=2,
                                               down_cooldown_s=0.5)
        mgr.slots[0].port, mgr.slots[0].base_url = (
            p0, f"http://127.0.0.1:{p0}")
        mgr.slots[1].port, mgr.slots[1].base_url = (
            p1, f"http://127.0.0.1:{p1}")
        mgr.drain_timeout = 2.0
        for slot in mgr.slots:
            mgr._launch(slot, "bundle-a")
        mgr.router.health_pass()
        mgr.router.health_pass()
        assert sum(1 for w in mgr.router.workers() if w.routable) == 2
        scrape.value = _signals(routable=2, queue=0)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "down"
        assert len(mgr.slots) == 1
        # at min: calm ticks keep holding, never below min_workers
        clock.now += 5.0
        scrape.value = _signals(routable=1, queue=0)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "hold"
        assert len(mgr.slots) == 1

    def test_scale_down_drains_the_least_loaded_worker(self, tmp_path,
                                                       spawn_worker):
        # the satellite edge: w0 is busy (queue 7), w1 idle — the retire
        # pick must be w1, through the drain handshake
        busy, p0 = spawn_worker()
        idle, p1 = spawn_worker()
        busy.queue_depth = 7
        mgr, auto, clock, scrape = self._fleet(tmp_path, slots=2)
        mgr.slots[0].port, mgr.slots[0].base_url = (
            p0, f"http://127.0.0.1:{p0}")
        mgr.slots[1].port, mgr.slots[1].base_url = (
            p1, f"http://127.0.0.1:{p1}")
        mgr.drain_timeout = 2.0
        for slot in mgr.slots:
            mgr._launch(slot, "bundle-a")
        mgr.router.health_pass()  # admit
        mgr.router.health_pass()  # scrape loads
        assert mgr.scale_down_one() is True
        assert [s.id for s in mgr.slots] == ["w0"]  # the busy one stayed
        assert idle.draining  # the retired worker got POST /admin/drain
        assert not busy.draining
        with pytest.raises(KeyError):
            mgr.router.worker("w1")  # removed from the router

    def test_resize_queues_behind_a_rolling_upgrade(self, tmp_path):
        # the satellite edge: a roll holds the cycle lock for minutes —
        # a resize decided mid-roll must defer, not interleave
        mgr, auto, clock, scrape = self._fleet(tmp_path)
        scrape.value = _signals(routable=1, queue=8)
        assert self._tick(auto, clock) == "hold"
        assert mgr._cycle_lock.acquire(blocking=False)  # "roll in flight"
        try:
            assert self._tick(auto, clock) == "deferred_roll"
            assert len(mgr.slots) == 1  # nothing interleaved
        finally:
            mgr._cycle_lock.release()
        # first post-roll tick applies the queued resize
        assert self._tick(auto, clock) == "up"
        assert len(mgr.slots) == 2

    def test_brownout_enters_escalates_and_exits_only_at_max(
            self, tmp_path):
        mgr, auto, clock, scrape = self._fleet(tmp_path, slots=3)
        r = mgr.router
        scrape.value = _signals(routable=3, queue=30)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_enter"
        assert r.brownout_level == 1
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_escalate"
        assert r.brownout_level == 2
        assert self._tick(auto, clock) == "hold"  # deepest tier: hold
        # scale-down is forbidden while browned out; calm ticks release
        # the tiers one by one instead
        scrape.value = _signals(routable=3, queue=0)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_exit"
        assert r.brownout_level == 1
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_exit"
        assert r.brownout_level == 0
        assert len(mgr.slots) == 3  # no resize happened under brownout

    def test_brownout_does_not_latch_on_its_own_sheds(self, tmp_path):
        # the self-inflicted-burn trap: tier-1 sheds are honest 503s the
        # SLO rightly counts as failures — if the controller read that
        # burn as "still overloaded", a trickle of large slabs would
        # hold brownout (and max size) forever after the real overload
        # ended. Under brownout, pressure alone is the evidence.
        mgr, auto, clock, scrape = self._fleet(tmp_path, slots=3)
        r = mgr.router
        scrape.value = _signals(routable=3, queue=30)
        self._tick(auto, clock)
        assert self._tick(auto, clock) == "brownout_enter"
        assert r.brownout_level == 1
        # overload over, but our own sheds keep the burn >= 1 on both
        # windows: calm ticks must still accumulate and release the tier
        scrape.value = _signals(routable=3, queue=0, burn=5.0)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_exit"
        assert r.brownout_level == 0
        # out of brownout the burn signal re-arms: sustained burn counts
        # as overload again (and at max size that means re-entry)
        assert self._tick(auto, clock) == "hold"
        assert self._tick(auto, clock) == "brownout_enter"

    def test_status_surfaces_the_loop_state(self, tmp_path):
        mgr, auto, clock, scrape = self._fleet(tmp_path)
        scrape.value = _signals(routable=1, queue=8)
        self._tick(auto, clock)
        body = mgr.status()["autoscaler"]
        assert body["min_workers"] == 1 and body["max_workers"] == 3
        assert body["up_streak"] == 1
        assert body["last_decision"] == "hold"
        assert body["signals"]["queue_depth"] == 8

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AutoscalerConfig(min_workers=3, max_workers=2).validate()
        with pytest.raises(ValueError):
            AutoscalerConfig(up_pressure=1.0, down_pressure=2.0).validate()
        with pytest.raises(ValueError):
            AutoscalerConfig(interval_s=0.0).validate()
        with pytest.raises(ValueError):
            AutoscalerConfig(brownout_exit_ticks=0).validate()
        with pytest.raises(ValueError):
            AutoscalerConfig(up_cooldown_s=-1.0).validate()


class TestBrownoutRouter:
    def test_tier1_sheds_large_sample_slabs_only(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        r.set_brownout(1, max_rows=2)
        big = json.dumps({"data": [[0.5]] * 3}).encode()
        status, payload = r.handle("POST", "/v1/sample", big)
        assert status == 503
        assert b"brownout" in payload
        # small slabs still flow, and classify is never slab-shed
        assert r.handle("POST", "/v1/sample",
                        json.dumps({"data": [[0.5]]}).encode())[0] == 200
        assert r.handle("POST", "/v1/classify", big)[0] == 200
        m = r.metrics()
        assert m["brownout_shed"] == 1 and m["brownout_level"] == 1

    def test_tier2_caps_effective_deadlines(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        r.set_brownout(2, max_rows=64, deadline_s=0.25)
        r.handle("POST", "/v1/sample",
                 json.dumps({"data": [[0.5]], "timeout": 9.0}).encode())
        r.handle("POST", "/v1/sample",
                 json.dumps({"data": [[0.5]]}).encode())
        r.handle("POST", "/v1/sample",
                 json.dumps({"data": [[0.5]], "timeout": 0.1}).encode())
        touts = [pl.get("timeout") for pl in b.payloads]
        # 9.0 clamped, missing injected, 0.1 (already tighter) untouched
        assert touts == [0.25, 0.25, 0.1]

    def test_brownout_surfaces_in_healthz_and_gauge(self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        assert r.healthz()["status"] == "ok"
        r.set_brownout(1, max_rows=16)
        body = r.healthz()
        assert body["status"] == "brownout"
        assert body["brownout"] == {"active": True, "level": 1,
                                    "max_sample_rows": 16,
                                    "deadline_cap_s": 1.0}
        snap = get_registry().snapshot()
        [series] = snap["fleet_brownout"]["series"]
        assert series["value"] == 1.0
        r.set_brownout(0)
        assert r.healthz()["status"] == "ok"
        assert r.healthz()["brownout"]["active"] is False

    def test_brownout_off_passes_everything_through(self, spawn_worker):
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        big = json.dumps({"data": [[0.5]] * 100}).encode()
        assert r.handle("POST", "/v1/sample", big)[0] == 200
        assert r.metrics()["brownout_shed"] == 0

    def test_malformed_body_passes_to_the_worker(self, spawn_worker):
        # admission control must not eat the worker's 400: garbage bodies
        # pass through untouched even in brownout
        b, p = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        r.set_brownout(2)
        status, _ = r.handle("POST", "/v1/sample", b"not json{{{")
        assert status == 200  # the fake worker answers everything
        assert b.hits == 1

    def test_brownout_shed_burns_the_slo(self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig

        b, p = spawn_worker()
        r = _router(slo_config=SLOConfig(availability_target=0.9,
                                         fast_window_s=30.0,
                                         slow_window_s=60.0))
        r.add_worker("w0", f"http://127.0.0.1:{p}")
        r.health_pass()
        r.set_brownout(1, max_rows=1)
        big = json.dumps({"data": [[0.5]] * 4}).encode()
        for _ in range(4):
            assert r.handle("POST", "/v1/sample", big)[0] == 503
        slo = r.slo.snapshot()
        assert slo["totals"]["failed"] == 4  # honest 503s burn budget


class TestSpawnFailureBackoff:
    def _manager(self, tmp_path, port, procs):
        def spawn(slot, bundle):
            proc = _FakeProc()
            proc._alive = False  # dies before ever becoming routable
            procs.append(proc)
            return proc

        r = _router()
        return FleetManager(r, str(tmp_path / "store"), num_workers=1,
                            ports=[port], spawn=spawn,
                            spawn_backoff_base=0.05,
                            spawn_backoff_max=0.08)

    def test_never_routable_death_backs_off_not_hot_loops(
            self, tmp_path, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        _, port = spawn_worker()
        procs = []
        mgr = self._manager(tmp_path, port, procs)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        mgr.bundle_path = "bundle-a"
        assert len(procs) == 1
        # first supervise pass observes the death: schedules, no relaunch
        mgr._supervise_once()
        assert slot.spawn_failures == 1
        assert len(procs) == 1  # NOT relaunched in the same pass
        # hammering supervise inside the backoff window stays a no-op —
        # the hot-loop shape JG021 polices
        for _ in range(5):
            mgr._supervise_once()
        assert len(procs) == 1
        time.sleep(0.06)  # past the 0.05s base backoff
        mgr._supervise_once()
        assert len(procs) == 2  # one relaunch, after the delay
        # it died again: the delay doubles (0.1 -> capped at 0.08)
        mgr._supervise_once()
        assert slot.spawn_failures == 2
        time.sleep(0.09)
        mgr._supervise_once()
        assert len(procs) == 3
        events = [e for e in mgr.events if e["event"] == "spawn_failure"]
        assert [e["failures"] for e in events] == [1, 2]
        assert events[1]["retry_in_s"] == 0.08  # capped
        snap = get_registry().snapshot()
        [series] = snap["fleet_spawn_failures_total"]["series"]
        assert series["value"] == 2.0

    def test_admission_resets_the_backoff_ladder(self, tmp_path,
                                                 spawn_worker):
        behavior, port = spawn_worker()
        r = _router()
        flaky = {"n": 0}

        def spawn(slot, bundle):
            flaky["n"] += 1
            proc = _FakeProc()
            proc._alive = flaky["n"] >= 2  # first boot dies, second lives
            return proc

        mgr = FleetManager(r, str(tmp_path / "store"), num_workers=1,
                           ports=[port], spawn=spawn,
                           spawn_backoff_base=0.02, spawn_backoff_max=1.0)
        slot = mgr.slots[0]
        mgr._launch(slot, "bundle-a")
        mgr.bundle_path = "bundle-a"
        mgr._supervise_once()  # death observed, backoff scheduled
        assert slot.spawn_failures == 1
        time.sleep(0.03)
        mgr._supervise_once()  # relaunch — this process lives
        r.health_pass()  # probe admits it
        mgr._supervise_once()  # supervision observes "closed"
        assert slot.ever_routable
        assert slot.spawn_failures == 0  # the ladder reset
        assert slot.next_launch_at is None


# ===========================================================================
# the alert plane on the router (telemetry/alerts.py)
# ===========================================================================

def _attach_default_alerts(router, **rule_kw):
    from gan_deeplearning4j_tpu.telemetry.alerts import (
        AlertManager,
        default_fleet_rules,
    )

    mgr = AlertManager(default_fleet_rules(
        annotate_member=router.annotate_member, **rule_kw))
    router.attach_alerts(mgr)
    return mgr


class TestAlertPlane:
    def test_disabled_plane_costs_zero_new_series(self, spawn_worker):
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        behavior, port = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        r.health_pass()
        r.health_pass()
        for _ in range(3):
            assert _post_sample(r)[0] == 200
        baseline = get_registry().series_count()
        for _ in range(3):
            _post_sample(r)
            r.health_pass()
        # no alert manager attached: serving + health traffic allocates
        # nothing new (the member gauges' series already exist from the
        # first pass — they are the PR 15 satellite, not alert-gated)
        assert get_registry().series_count() == baseline

    def test_member_gauges_refreshed_and_removed(self, spawn_worker):
        behavior, port = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        r.health_pass()  # probe admits
        r.health_pass()  # scrape lands
        view = r.alert_view()
        [up] = view["fleet_member_routable"]["series"]
        assert up["labels"] == {"worker": "w0"} and up["value"] == 1.0
        [age] = view["fleet_member_scrape_age_seconds"]["series"]
        assert age["value"] >= 0.0
        r.remove_worker("w0")
        assert r.alert_view()["fleet_member_routable"]["series"] == []
        assert (r.alert_view()["fleet_member_scrape_age_seconds"]["series"]
                == [])

    def test_member_signals_prunes_series_recreated_by_a_race(
            self, spawn_worker):
        # review-caught: a member_signals pass racing remove_worker can
        # re-create the retired member's gauge series AFTER the removal
        # — with the ref gone nothing would ever touch it again, and
        # worker_down would page forever on a scale-down. The next pass
        # must reconcile the series set against the live worker set.
        behavior, port = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        r.health_pass()
        r.remove_worker("w0")
        # simulate the race's leftovers: stray series for a gone member
        r._g_member_routable.labels(worker="w0").set(0.0)
        r._g_member_scrape_age.labels(worker="w0").set(42.0)
        r.member_signals()
        assert r.alert_view()["fleet_member_routable"]["series"] == []
        assert (r.alert_view()["fleet_member_scrape_age_seconds"]["series"]
                == [])

    def test_autoscaler_scrape_shares_member_signals(self, spawn_worker):
        behavior, port = spawn_worker()
        behavior.queue_depth = 3
        behavior.in_flight = 2
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        r.health_pass()
        r.health_pass()
        mgr = FleetManager(r, "/nonexistent-store-root",
                           num_workers=1, ports=[port],
                           spawn=lambda slot, bundle: _FakeProc(),
                           autoscale=AutoscalerConfig(min_workers=1,
                                                      max_workers=2))
        signals = mgr.autoscaler._default_scrape()
        expected = r.member_signals()
        assert signals["routable"] == expected["routable"] == 1
        assert signals["queue_depth"] == expected["queue_depth"] == 3
        # in_flight is the ROUTER-side count (requests it is running
        # there now), same as the pre-seam scrape read — none here
        assert signals["in_flight"] == expected["in_flight"] == 0
        assert "availability" in signals["burn_rates"]

    def test_worker_down_fires_with_exemplar_and_annotations(
            self, spawn_worker):
        behavior, port = spawn_worker()
        # long reopen: the fake's /healthz still answers while its /v1
        # path drops connections, so a half-open probe would re-admit it
        # mid-test and clear the very alert being asserted
        r = _router(max_attempts=2,
                    breaker_kwargs={"reopen_after": 30.0})
        ref = r.add_worker("w0", f"http://127.0.0.1:{port}", pid=4242)
        _attach_default_alerts(r, probe_interval_s=1.0)
        r.health_pass()
        r.health_pass()
        assert _post_sample(r)[0] == 200  # arms worker_down (healthy once)
        behavior.mode = "die"  # connection drops mid-request from now on
        for _ in range(4):
            _post_sample(r)  # failures: breaker trips + exemplars record
        assert not ref.routable
        for _ in range(4):
            r.health_pass()  # evaluation ticks: pending -> firing
        [entry] = [e for e in r.alerts.active()
                   if e["alert"] == "worker_down"]
        assert entry["state"] == "firing"
        assert entry["labels"] == {"worker": "w0"}
        assert entry["annotations"]["pid"] == 4242
        exemplars = entry["exemplars"]
        assert exemplars and all(e["worker"] == "w0" for e in exemplars)
        assert all(e["pid"] == 4242 for e in exemplars)
        assert all(e["trace_id"] for e in exemplars)
        # healthz carries the compact block
        block = r.healthz()["alerts"]
        assert block["ok"] is False
        # the failed proxies also burn the availability SLO — both fire
        assert "worker_down" in {f["alert"] for f in block["firing"]}

    def test_alert_http_routes(self, spawn_worker):
        import urllib.request

        behavior, port = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        srv = make_router_server(r, port=0)
        rport = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            # without the plane: an honest 404, not a crash
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/alerts", timeout=5.0)
                assert False, "expected 404"
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
            _attach_default_alerts(r)
            r.health_pass()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/alerts",
                    timeout=5.0) as resp:
                doc = json.loads(resp.read())
            assert {x["name"] for x in doc["rules"]} >= {"worker_down"}
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/alerts?format=prom",
                    timeout=5.0) as resp:
                assert b"# TYPE ALERTS gauge" in resp.read()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_fleet_scope_keeps_member_labeled_gauges(self, spawn_worker):
        # the aggregate setdefault fix end-to-end: the router's
        # per-member gauges survive the fleet merge with their own
        # worker labels instead of being relabeled worker="router"
        behavior, port = spawn_worker()
        r = _router()
        r.add_worker("w0", f"http://127.0.0.1:{port}")
        r.health_pass()
        r.health_pass()
        snap = r.fleet_metrics_snapshot()
        routable = {s["labels"]["worker"]: s["value"]
                    for s in snap["fleet_member_routable"]["series"]}
        assert routable == {"w0": 1.0}
        ages = {s["labels"]["worker"]
                for s in snap["fleet_member_scrape_age_seconds"]["series"]}
        assert ages == {"w0"}
